"""Process fleet (pydcop_tpu.serve.procfleet).

Three layers, cheapest first:

* pure helpers — JSON-safe wire conversion, dims round-trip, the
  exit-code taxonomy on stub processes (no spawn, no socket);
* a thread-hosted :class:`ReplicaWorker` over a real hub socket —
  the child protocol (ready / submit→complete / reject / stop)
  without paying a process spawn;
* ONE real-subprocess end-to-end test pinning the ISSUE acceptance
  criteria: ``kill -9`` of a whole replica process mid-flight →
  survivors complete every job bit-identically with a finite RTO and
  the watchdog relaunches; a cold-joined replica bootstraps from the
  shared artifact store and reaches warmth with ZERO XLA compiles
  (``misses == 0``, ``artifact_hits == entries``).

The broader chaos run (fault-plan-driven kill_process /
partition_socket / corrupt_artifact) is ``slow``-marked.
"""
import os
import signal
import threading
import time

import numpy as np
import pytest

from pydcop_tpu.batch.bucketing import InstanceDims
from pydcop_tpu.batch.engine import BatchItem, adapter_for
from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime.faults import KILL_EXIT_CODE, Fault, FaultPlan
from pydcop_tpu.serve.procfleet import (
    ProcessFleet,
    ProcessReplicaHandle,
    ReplicaWorker,
    _dims_from_wire,
    _dims_to_wire,
    _json_safe,
)
from pydcop_tpu.serve.wire import JournalHub

TUTO = os.path.join(os.path.dirname(__file__), "..", "instances",
                    "graph_coloring_tuto.yaml")
TUTO = os.path.abspath(TUTO)
LIMIT = 63


def _standalone(dcop, algo, seed, params=None):
    spec = adapter_for(algo).build_spec(
        BatchItem(dcop, algo, algo_params=params, seed=seed)
    )
    return spec.solver.run(max_cycles=LIMIT)


def _wait(pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


# --------------------------------------------------------------------------
# helpers + taxonomy (no spawn, no socket)
# --------------------------------------------------------------------------


class TestWireHelpers:
    def test_json_safe_strips_numpy(self):
        out = _json_safe({
            "i": np.int64(7), "f": np.float64(1.5),
            "nest": [np.int32(1), (np.float32(2.0),)],
        })
        assert out == {"i": 7, "f": 1.5, "nest": [1, [2.0]]}
        assert type(out["i"]) is int
        assert type(out["f"]) is float

    def test_dims_roundtrip(self):
        d = InstanceDims(graph_type="constraints_hypergraph", D=3,
                         arities=(2, 3), V=5, F=(4, 2), M=6)
        assert _dims_from_wire(_dims_to_wire(d)) == d


class _StubProc:
    """Just enough Popen surface for the taxonomy properties."""

    def __init__(self, rc):
        self._rc = rc
        self.pid = 12345

    def poll(self):
        return self._rc

    def kill(self):
        self._rc = -signal.SIGKILL


def _handle(rc):
    return ProcessReplicaHandle(
        name="replica-0", index=0, service=None,
        journal_dir="", hb_path="", proc=_StubProc(rc),
    )


class TestExitTaxonomy:
    def test_signal_death_is_retryable(self):
        h = _handle(-signal.SIGKILL)
        assert h.dead and h.retryable
        assert "signal 9" in h.down_reason

    def test_injected_kill_exit_code_is_retryable(self):
        h = _handle(KILL_EXIT_CODE)
        assert h.dead and h.retryable
        assert "injected kill" in h.down_reason

    def test_clean_exit_is_not_retryable(self):
        h = _handle(0)
        assert h.dead and not h.retryable
        assert h.down_reason == "process exited"

    def test_config_failure_is_not_retryable(self):
        h = _handle(2)
        assert h.dead and not h.retryable
        assert "rc=2" in h.down_reason

    def test_live_process_is_not_dead(self):
        h = _handle(None)
        assert not h.dead
        h.kill()
        assert h.dead and h.retryable

    def test_process_fault_kinds_registered(self):
        for kind in ("kill_process", "partition_socket",
                     "corrupt_artifact"):
            assert kind in ProcessFleet._INJECT_KINDS
        plan = FaultPlan(faults=[
            Fault(kind="kill_process", replica=0, cycle=1),
            Fault(kind="partition_socket", replica=1, cycle=2,
                  duration=1.0),
            Fault(kind="corrupt_artifact", cycle=3),
        ])
        assert len(plan.process_faults()) == 3
        assert plan.fleet_faults() == []


# --------------------------------------------------------------------------
# thread-hosted ReplicaWorker over a real socket
# --------------------------------------------------------------------------


class _WorkerHost:
    def __init__(self, tmp, **kw):
        self.records = []
        self.hub = JournalHub(on_record=self._tap)
        self._stop = threading.Event()
        self._pump = threading.Thread(target=self._pump_loop,
                                      daemon=True)
        self._pump.start()
        kw.setdefault("lanes", 2)
        kw.setdefault("max_cycles", LIMIT)
        kw.setdefault("stats_interval", 0.1)
        self.worker = ReplicaWorker(
            ("127.0.0.1", self.hub.port), "w0",
            journal_dir=os.path.join(str(tmp), "w0"),
            heartbeat_path=os.path.join(str(tmp), "w0.hb"),
            **kw,
        )
        self._wt = threading.Thread(target=self.worker.run,
                                    daemon=True)
        self._wt.start()

    def _tap(self, client, body):
        self.records.append((client, body))

    def _pump_loop(self):
        while not self._stop.is_set():
            self.hub.pump(0.01)

    def events(self, evt):
        return [b for _c, b in self.records if b.get("evt") == evt]

    def close(self):
        self.hub.send("w0", {"cmd": "stop"})
        self._wt.join(timeout=15)
        self._stop.set()
        self._pump.join(timeout=5)
        self.hub.stop()


@pytest.fixture
def host(tmp_path):
    h = _WorkerHost(tmp_path)
    yield h
    h.close()


def _submit_body(jid, seed=0, source_file=TUTO, algo="dsa"):
    return {
        "cmd": "submit", "jid": jid, "algo": algo,
        "algo_params": {}, "seed": seed, "tenant": "default",
        "priority": 0, "deadline_s": None, "label": None,
        "source_file": source_file, "stream": False, "restore": None,
    }


class TestReplicaWorkerProtocol:
    def test_ready_then_complete_bit_identical(self, host):
        assert _wait(lambda: host.events("ready"))
        ready = host.events("ready")[0]
        assert ready["pid"] == os.getpid()  # thread-hosted
        assert set(ready["abi"]) == {"jax", "jaxlib", "backend"}

        host.hub.send("w0", _submit_body("job-000001", seed=3))
        assert _wait(lambda: host.events("complete"), timeout=120)
        done = host.events("complete")[0]
        assert done["jid"] == "job-000001"
        exp = _standalone(load_dcop_from_file([TUTO]), "dsa", 3)
        got = done["result"]
        assert got["status"] == exp.status
        assert got["assignment"] == exp.assignment
        assert got["cost"] == exp.cost

    def test_bad_source_file_rejects_structuredly(self, host):
        assert _wait(lambda: host.events("ready"))
        host.hub.send(
            "w0", _submit_body("job-000002",
                               source_file="/nonexistent/x.yaml")
        )
        assert _wait(lambda: host.events("reject"))
        rej = host.events("reject")[0]
        assert rej["jid"] == "job-000002"
        assert rej["error"]

    def test_heartbeat_beats_and_stats_stream(self, host, tmp_path):
        assert _wait(lambda: host.events("ready"))
        hb = os.path.join(str(tmp_path), "w0.hb")
        assert _wait(lambda: os.path.exists(hb))
        assert _wait(lambda: len(host.events("stats")) >= 2)
        st = host.events("stats")[-1]
        assert "serve" in st and "cache" in st

    def test_stop_command_ends_run_loop(self, host):
        assert _wait(lambda: host.events("ready"))
        host.hub.send("w0", {"cmd": "stop"})
        assert _wait(lambda: not host._wt.is_alive(), timeout=15)


# --------------------------------------------------------------------------
# the real thing: child OS processes
# --------------------------------------------------------------------------


def _drain(fleet, max_ticks=6000):
    for i in range(max_ticks):
        if not fleet.tick():
            return i
        time.sleep(0.01)
    raise AssertionError("fleet did not drain")


class TestProcessFleetEndToEnd:
    def test_kill9_reseat_relaunch_and_zero_compile_cold_join(
        self, tmp_path
    ):
        """The ISSUE acceptance pins, one fleet bring-up:

        1. kill -9 of a WHOLE replica process with 4 jobs in flight →
           every job completes bit-identically on the survivor, the
           RTO is recorded finite, the watchdog relaunches the slot;
        2. a cold-joined replica prewarms purely from the shared
           artifact store: ``misses == 0`` and ``artifact_hits ==
           entries`` — zero XLA compiles before its first job.
        """
        dcop = load_dcop_from_file([TUTO])
        base = {s: _standalone(dcop, "dsa", s) for s in range(4)}

        fleet = ProcessFleet(
            replicas=2, lanes=4, max_cycles=LIMIT,
            journal_dir=str(tmp_path), backoff_base=0.1,
        )
        try:
            assert fleet.wait_ready(timeout=120), "replicas not ready"

            jids = [
                fleet.submit(dcop, "dsa", seed=s, source_file=TUTO)
                for s in range(4)
            ]
            fleet.tick()
            h0 = fleet.handle(0)
            os.kill(h0.proc.pid, signal.SIGKILL)
            _drain(fleet)

            for s, jid in enumerate(jids):
                res = fleet.result(jid, timeout=30)
                assert res.status == base[s].status
                assert res.assignment == base[s].assignment, \
                    f"seed {s} not bit-identical after kill -9"
                assert res.cost == base[s].cost

            m = fleet.metrics()
            fl = m["fleet"]
            assert fl["replicas_down"] >= 1, fl
            assert fl["jobs_reseated"] >= 1, fl
            assert m["recoveries"], "no RTO record for the kill"
            rto = m["recoveries"][-1]["rto_s"]
            assert rto is not None and 0 <= rto < 300

            # the SIGKILL is retryable: the slot relaunches under a
            # fresh incarnation name and comes back ready
            assert _wait(
                lambda: (fleet.tick() or True)
                and fleet.metrics()["fleet"]["replicas_relaunched"]
                >= 1,
                timeout=60,
            ), fleet.metrics()["fleet"]

            # cold join: warm purely from the shared artifact store
            name = fleet.add_replica()
            assert fleet.wait_ready(timeout=120)
            hc = fleet.handle(name)
            hc.service.prewarm([(TUTO, "dsa", {})])
            assert _wait(
                lambda: (fleet.tick() or True)
                and hc.service.cache.stats().get("entries", 0) > 0,
                timeout=90,
            ), hc.service.cache.stats()
            stats = hc.service.cache.stats()
            assert stats["misses"] == 0, stats       # ZERO compiles
            assert stats["artifact_hits"] == stats["entries"], stats

            jid = fleet.submit(dcop, "dsa", seed=9, source_file=TUTO)
            _drain(fleet)
            exp = _standalone(dcop, "dsa", 9)
            res = fleet.result(jid, timeout=30)
            assert res.assignment == exp.assignment
            assert res.cost == exp.cost
        finally:
            fleet.stop(drain=False)


@pytest.mark.slow
class TestProcessFleetChaos:
    def test_fault_plan_drives_process_faults(self, tmp_path):
        """The twin chaos kinds end to end: a planned kill_process
        fires and recovers; partition_socket severs + heals with
        nothing lost; corrupt_artifact damages an exported runner and
        the CRC check rejects it into a recompile."""
        dcop = load_dcop_from_file([TUTO])
        base = {s: _standalone(dcop, "dsa", s) for s in range(6)}
        plan = FaultPlan(seed=7, faults=[
            Fault(kind="kill_process", replica=0, cycle=3),
            Fault(kind="partition_socket", replica=1, cycle=6,
                  duration=0.5),
        ])
        fleet = ProcessFleet(
            replicas=2, lanes=4, max_cycles=LIMIT,
            journal_dir=str(tmp_path), fault_plan=plan,
            backoff_base=0.1,
        )
        try:
            assert fleet.wait_ready(timeout=120)
            jids = [
                fleet.submit(dcop, "dsa", seed=s, source_file=TUTO)
                for s in range(6)
            ]
            _drain(fleet, max_ticks=12000)
            for s, jid in enumerate(jids):
                res = fleet.result(jid, timeout=30)
                assert res.assignment == base[s].assignment, \
                    f"seed {s} diverged under chaos"
            fl = fleet.metrics()["fleet"]
            assert fl["faults_injected"] >= 2, fl
            assert fl["replicas_down"] >= 1, fl
            assert fl["socket_partitions"] >= 1, fl

            # corrupt an exported artifact, then cold-join: the CRC
            # check rejects it loudly and the replica recompiles
            arts = [n for n in os.listdir(fleet.artifact_dir)
                    if n.endswith(".rnr")]
            assert arts, "no artifacts exported"
            from pydcop_tpu.serve.artifacts import (
                corrupt_artifact_file,
            )
            assert corrupt_artifact_file(
                os.path.join(fleet.artifact_dir, arts[0])
            )
            name = fleet.add_replica()
            assert fleet.wait_ready(timeout=120)
            hc = fleet.handle(name)
            hc.service.prewarm([(TUTO, "dsa", {})])
            assert _wait(
                lambda: (fleet.tick() or True)
                and hc.service.cache.stats().get("entries", 0) > 0,
                timeout=120,
            )
            stats = hc.service.cache.stats()
            rejected = stats.get("artifacts", {}).get(
                "rejected_corrupt", 0
            )
            assert rejected >= 1, stats
            jid = fleet.submit(dcop, "dsa", seed=11, source_file=TUTO)
            _drain(fleet)
            exp = _standalone(dcop, "dsa", 11)
            assert fleet.result(jid, timeout=30).assignment \
                == exp.assignment
        finally:
            fleet.stop(drain=False)
