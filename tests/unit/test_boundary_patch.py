"""Incremental boundary re-analysis (ISSUE 8): a mutation dirties only
its own cut edges, so ``parallel/boundary`` patches the existing
analysis/exchange plan instead of recomputing it — and the patched
result must be IDENTICAL to a fresh analysis of the mutated
assignment.  Plus the sharded engines' warm factor-edit hook
(ShardedMaxSum.edit_factor: one stacked slab row rewritten, operands
re-staged, compiled runner untouched).
"""
import numpy as np
import pytest

from pydcop_tpu.ops.compile import compile_binary_from_arrays
from pydcop_tpu.parallel.boundary import (
    analyze_boundary,
    build_exchange_plan,
    patch_boundary,
    patch_exchange_plan,
)


def ring_instance(V=20, F=30, D=3, seed=1):
    rng = np.random.default_rng(seed)
    ei = rng.integers(0, V, F)
    ej = (ei + 1 + rng.integers(0, V - 1, F)) % V
    mats = rng.uniform(0, 5, (F, D, D)).astype(np.float32)
    return ei, ej, mats, compile_binary_from_arrays(ei, ej, mats, V)


class TestPatchBoundary:
    def _base(self):
        rng = np.random.default_rng(0)
        V, F = 24, 40
        vi = np.stack([rng.integers(0, V, F),
                       rng.integers(0, V, F)], 1).astype(np.int32)
        assign = (np.arange(F) % 3).astype(np.int64)
        return V, vi, assign

    def test_patch_equals_fresh_analysis(self):
        V, vi, assign = self._base()
        info = analyze_boundary([vi], [assign], V, 3, keep_touch=True)
        # move factor 5 to shard 2 with a different scope
        new_row = np.array([0, 13], np.int32)
        info2 = patch_boundary(
            info,
            removed=[(vi[5], int(assign[5]))],
            added=[(new_row, 2)],
        )
        vi2 = vi.copy()
        vi2[5] = new_row
        assign2 = assign.copy()
        assign2[5] = 2
        fresh = analyze_boundary([vi2], [assign2], V, 3,
                                 keep_touch=True)
        for f in ("owner", "boundary_mask", "touch_count", "touch"):
            assert np.array_equal(getattr(info2, f), getattr(fresh, f)), f
        assert info2.n_boundary == fresh.n_boundary
        assert info2.n_touched == fresh.n_touched
        assert info2.cut_fraction == pytest.approx(fresh.cut_fraction)
        # the original analysis is untouched (pure patch)
        assert info.n_boundary == analyze_boundary(
            [vi], [assign], V, 3).n_boundary

    def test_patch_requires_keep_touch(self):
        V, vi, assign = self._base()
        info = analyze_boundary([vi], [assign], V, 3)
        with pytest.raises(ValueError, match="keep_touch"):
            patch_boundary(info, removed=[(vi[0], int(assign[0]))])

    def test_stale_removal_detected(self):
        V, vi, assign = self._base()
        info = analyze_boundary([vi], [assign], V, 3, keep_touch=True)
        ghost = np.array([vi[0, 0], vi[0, 1]], np.int32)
        wrong_shard = (int(assign[0]) + 1) % 3
        # removing from a shard that never counted those endpoints
        # (enough times) must be caught, not silently go negative
        info2 = info
        with pytest.raises(ValueError, match="stale"):
            for _ in range(5):
                info2 = patch_boundary(
                    info2, removed=[(ghost, wrong_shard)])

    def test_add_remove_roundtrip_is_identity(self):
        V, vi, assign = self._base()
        info = analyze_boundary([vi], [assign], V, 3, keep_touch=True)
        row = np.array([3, 17], np.int32)
        info2 = patch_boundary(info, added=[(row, 1)])
        info3 = patch_boundary(info2, removed=[(row, 1)])
        for f in ("owner", "boundary_mask", "touch_count", "touch"):
            assert np.array_equal(getattr(info3, f), getattr(info, f)), f


class TestPatchExchangePlan:
    def _pairwise(self):
        V = 12
        vi = np.array([[0, 1], [1, 2], [2, 3], [3, 4], [4, 5], [5, 6],
                       [6, 7], [7, 8]], np.int32)
        assign = np.array([0, 0, 0, 1, 1, 1, 2, 2], np.int64)
        return V, vi, assign

    @staticmethod
    def _pair_payloads(plan):
        out = {}
        for r, perms in enumerate(plan.rounds):
            for (a, b) in perms:
                k = int(plan.recv_valid[b, r].sum())
                out[(a, b)] = list(plan.send_idx[a, r, :k])
        return out

    def test_same_pair_structure_is_patched_in_place(self):
        V, vi, assign = self._pairwise()
        info = analyze_boundary([vi], [assign], V, 3, keep_touch=True)
        plan = build_exchange_plan(info, [vi], [assign])
        assert plan is not None
        # move one cut column: pair set unchanged, columns change
        new_row = np.array([2, 4], np.int32)
        info2 = patch_boundary(info, removed=[(vi[2], 0)],
                               added=[(new_row, 0)])
        plan2, patched = patch_exchange_plan(plan, info2)
        assert patched, "same pair structure must patch, not rebuild"
        assert plan2.rounds == plan.rounds  # schedule reused verbatim
        vi2 = vi.copy()
        vi2[2] = new_row
        fresh = build_exchange_plan(
            analyze_boundary([vi2], [assign], V, 3), [vi2], [assign])
        assert self._pair_payloads(plan2) == self._pair_payloads(fresh)

    def test_new_pair_rebuilds(self):
        V, vi, assign = self._pairwise()
        info = analyze_boundary([vi], [assign], V, 3, keep_touch=True)
        plan = build_exchange_plan(info, [vi], [assign])
        # a factor bridging shards 0 and 2: a pair the plan never had
        row = np.array([0, 8], np.int32)
        info2 = patch_boundary(info, added=[(row, 0)])
        plan2, patched = patch_exchange_plan(plan, info2)
        assert not patched
        assert plan2 is not None
        pairs = set(self._pair_payloads(plan2))
        assert (0, 2) in pairs or (2, 0) in pairs

    def test_non_pairwise_returns_none(self):
        V = 8
        # one variable shared by all three shards
        vi = np.array([[0, 1], [0, 2], [0, 3]], np.int32)
        assign = np.array([0, 1, 2], np.int64)
        info = analyze_boundary([vi], [assign], V, 3, keep_touch=True)
        plan2, patched = patch_exchange_plan(None, info)
        assert plan2 is None and not patched


class TestShardedWarmEdit:
    def test_edit_factor_matches_fresh_engine(self):
        from pydcop_tpu.parallel import ShardedMaxSum, build_mesh

        ei, ej, mats, t = ring_instance()
        eng = ShardedMaxSum(t, build_mesh(2), damping=0.5,
                            use_packed=False)
        v1, q, r = eng.run(cycles=8)
        rng = np.random.default_rng(9)
        new_tab = rng.uniform(0, 5, mats.shape[1:]).astype(np.float32)
        eng.edit_factor(0, 7, new_tab)
        v2, _, _ = eng.run(cycles=8, q=q, r=r)

        mats2 = mats.copy()
        mats2[7] = new_tab
        fresh = ShardedMaxSum(
            compile_binary_from_arrays(ei, ej, mats2, t.n_vars),
            build_mesh(2), damping=0.5, use_packed=False)
        vf, qf, rf = fresh.run(cycles=8)
        vf2, _, _ = fresh.run(cycles=8, q=qf, r=rf)
        assert np.array_equal(np.asarray(v2), np.asarray(vf2))

    def test_edit_factor_compact_mode(self):
        from pydcop_tpu.parallel import ShardedMaxSum, build_mesh

        ei, ej, mats, t = ring_instance(seed=4)
        rng = np.random.default_rng(10)
        new_tab = rng.uniform(0, 5, mats.shape[1:]).astype(np.float32)

        eng = ShardedMaxSum(t, build_mesh(2), damping=0.5,
                            use_packed=False, overlap="exact")
        v1, q, r = eng.run(cycles=8)
        eng.edit_factor(0, 7, new_tab)
        v2, _, _ = eng.run(cycles=8, q=q, r=r)

        mats2 = mats.copy()
        mats2[7] = new_tab
        dense = ShardedMaxSum(
            compile_binary_from_arrays(ei, ej, mats2, t.n_vars),
            build_mesh(2), damping=0.5, use_packed=False)
        vf, qf, rf = dense.run(cycles=8)
        vf2, _, _ = dense.run(cycles=8, q=qf, r=rf)
        assert np.array_equal(np.asarray(v2), np.asarray(vf2))

    def test_edit_factor_validates(self):
        from pydcop_tpu.parallel import ShardedMaxSum, build_mesh

        _ei, _ej, mats, t = ring_instance()
        eng = ShardedMaxSum(t, build_mesh(2), damping=0.5,
                            use_packed=False)
        with pytest.raises(ValueError, match="scope"):
            eng.edit_factor(0, 7, np.zeros((2, 2), np.float32))

    def test_sharded_graph_keeps_factor_rows_and_touch(self):
        from pydcop_tpu.parallel.mesh import shard_factor_graph

        _ei, _ej, _mats, t = ring_instance()
        st = shard_factor_graph(t, 2)
        rows = st.factor_rows[0]
        assert rows.shape[0] == t.buckets[0].n_factors
        assert (rows >= 0).all()
        # rows index the stacked slab: round-trip the tables
        stacked = np.asarray(st.buckets[0].tensors)
        orig = np.asarray(t.buckets[0].tensors)
        assert np.allclose(stacked[rows], orig)
        # boundary analysis retained its patchable counts
        assert st.boundary.touch is not None
