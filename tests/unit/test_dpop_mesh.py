"""Sharded DPOP sweep ≡ single-device sweep on the virtual 8-mesh."""
import numpy as np
import pytest

from pydcop_tpu.graph import pseudotree
from pydcop_tpu.ops.dpop_sweep import compile_sweep, run_sweep
from pydcop_tpu.parallel import ShardedDpopSweep, build_mesh

from tests.unit.test_dpop_sweep import brute_force_cost, random_dcop


def _assign_cost(dcop, plan, assign):
    names = plan.gid_to_name
    a = {
        n: list(dcop.variables[n].domain)[int(assign[i])]
        for i, n in enumerate(names)
    }
    _, cost = dcop.solution_cost(a, 10000000)
    return cost


@pytest.mark.parametrize("n_shards", [2, 8])
def test_sharded_sweep_bitmatches_single_device(n_shards):
    dcop = random_dcop(60, 25, dom_sizes=(2, 3), seed=9)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    assert plan is not None

    single, _ = run_sweep(plan)
    sharded = ShardedDpopSweep(plan, build_mesh(n_shards)).run()
    np.testing.assert_array_equal(sharded, single)


def test_sharded_sweep_is_optimal():
    dcop = random_dcop(12, 6, dom_sizes=(2, 3), seed=3)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    assert plan is not None
    assign = ShardedDpopSweep(plan, build_mesh(4)).run()
    assert _assign_cost(dcop, plan, assign) == brute_force_cost(dcop)


def test_sharded_sweep_max_mode():
    dcop = random_dcop(14, 6, dom_sizes=(2,), seed=11, objective="max")
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "max")
    assert plan is not None
    single, _ = run_sweep(plan)
    sharded = ShardedDpopSweep(plan, build_mesh(8)).run()
    np.testing.assert_array_equal(sharded, single)


def test_batch_not_divisible_by_shards():
    """Bmax not a multiple of n_shards exercises the row padding."""
    dcop = random_dcop(37, 11, dom_sizes=(3,), seed=21)
    tree = pseudotree.build_computation_graph(dcop)
    plan = compile_sweep(tree, dcop, "min")
    assert plan is not None
    single, _ = run_sweep(plan)
    sharded = ShardedDpopSweep(plan, build_mesh(8)).run()
    np.testing.assert_array_equal(sharded, single)
