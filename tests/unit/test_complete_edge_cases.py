"""Edge cases for the complete algorithms (dpop, syncbb, ncbb): negative
costs, max mode, unary-only problems, hard-infeasible instances, and
mixed domain sizes — all cross-checked against brute force.
"""
import itertools

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.runtime import solve_result

COMPLETE = ["dpop", "syncbb", "ncbb"]


def brute_force(dcop):
    names = sorted(dcop.variables)
    domains = [list(dcop.variables[n].domain) for n in names]
    sign = 1 if dcop.objective == "min" else -1
    best, best_cost = None, float("inf")
    for combo in itertools.product(*domains):
        asst = dict(zip(names, combo))
        _, cost = dcop.solution_cost(asst, 10000000)
        if sign * cost < best_cost:
            best, best_cost = asst, sign * cost
    return best, sign * best_cost


def binary_dcop(mats, objective="min", dom_sizes=None):
    """mats: {(i, j): matrix} over variables v0..vN."""
    n = max(max(i, j) for i, j in mats) + 1
    dom_sizes = dom_sizes or {}
    dcop = DCOP("edge", objective=objective)
    vs = []
    for i in range(n):
        size = dom_sizes.get(i, 2)
        d = Domain(f"d{i}", "v", list(range(size)))
        v = Variable(f"v{i}", d)
        vs.append(v)
        dcop.add_variable(v)
    for k, ((i, j), m) in enumerate(sorted(mats.items())):
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], np.asarray(m, dtype=float),
                               name=f"c{k}")
        )
    dcop.add_agents([AgentDef("a0")])
    return dcop


@pytest.mark.parametrize("algo", COMPLETE)
def test_negative_costs(algo):
    """Negative costs break naive B&B bounds; all three must stay exact
    (our syncbb uses admissible suffix bounds for exactly this)."""
    rng = np.random.default_rng(3)
    mats = {
        (0, 1): rng.uniform(-5, 5, (2, 2)),
        (1, 2): rng.uniform(-5, 5, (2, 2)),
        (2, 3): rng.uniform(-5, 5, (2, 2)),
        (0, 3): rng.uniform(-5, 5, (2, 2)),
    }
    dcop = binary_dcop(mats)
    _, expected = brute_force(dcop)
    res = solve_result(dcop, algo)
    assert res.cost == pytest.approx(expected)


@pytest.mark.parametrize("algo", COMPLETE)
def test_max_mode(algo):
    rng = np.random.default_rng(5)
    mats = {(0, 1): rng.uniform(0, 9, (2, 3)),
            (1, 2): rng.uniform(0, 9, (3, 2))}
    dcop = binary_dcop(mats, objective="max",
                       dom_sizes={0: 2, 1: 3, 2: 2})
    _, expected = brute_force(dcop)
    res = solve_result(dcop, algo)
    assert res.cost == pytest.approx(expected)


@pytest.mark.parametrize("algo", COMPLETE)
def test_mixed_domain_sizes(algo):
    rng = np.random.default_rng(7)
    mats = {
        (0, 1): rng.integers(0, 9, (2, 4)).astype(float),
        (1, 2): rng.integers(0, 9, (4, 3)).astype(float),
        (0, 2): rng.integers(0, 9, (2, 3)).astype(float),
    }
    dcop = binary_dcop(mats, dom_sizes={0: 2, 1: 4, 2: 3})
    _, expected = brute_force(dcop)
    res = solve_result(dcop, algo)
    assert res.cost == pytest.approx(expected)


@pytest.mark.parametrize("algo", COMPLETE)
def test_single_value_domains(algo):
    """Domains of size 1 leave no choice; solvers must not crash."""
    mats = {(0, 1): [[3.0, 7.0]], (1, 2): [[2.0], [9.0]]}
    dcop = binary_dcop(mats, dom_sizes={0: 1, 1: 2, 2: 1})
    _, expected = brute_force(dcop)
    res = solve_result(dcop, algo)
    assert res.cost == pytest.approx(expected)
    assert res.assignment["v0"] == 0 and res.assignment["v2"] == 0


@pytest.mark.parametrize("algo", COMPLETE)
def test_chain_vs_bruteforce_randomized(algo):
    """Longer chains with branching: 5 random topologies per algo."""
    for seed in range(5):
        rng = np.random.default_rng(seed + 20)
        n = 6
        mats = {}
        for i in range(1, n):
            p = int(rng.integers(0, i))
            mats[(p, i)] = rng.integers(0, 9, (2, 2)).astype(float)
        dcop = binary_dcop(mats)
        _, expected = brute_force(dcop)
        res = solve_result(dcop, algo)
        assert res.cost == pytest.approx(expected), (algo, seed)


def test_dpop_sweep_used_for_all_edge_cases():
    """The batched sweep engine (not just the fallback) must cover the
    edge cases above — verify it actually engages on one of them."""
    from pydcop_tpu.algorithms.dpop import DpopSolver

    rng = np.random.default_rng(3)
    mats = {(0, 1): rng.uniform(-5, 5, (2, 2)),
            (1, 2): rng.uniform(-5, 5, (2, 2))}
    dcop = binary_dcop(mats)
    solver = DpopSolver(dcop)
    res = solver.run()
    assert solver.last_engine == "sweep"
    _, expected = brute_force(dcop)
    assert res.cost == pytest.approx(expected)


@pytest.mark.parametrize("algo", COMPLETE)
def test_tie_dense_instance_returns_an_optimum(algo):
    """All-equal cost tables make every assignment optimal — heavy
    tie-breaking stress; the algorithms may pick any optimum but the
    COST must match brute force."""
    mats = {
        (0, 1): [[3, 3], [3, 3]],
        (1, 2): [[3, 3], [3, 3]],
        (0, 2): [[3, 3], [3, 3]],
    }
    dcop = binary_dcop(mats)
    res = solve_result(dcop, algo)
    _, bf_cost = brute_force(dcop)
    assert res.cost == bf_cost == 9


@pytest.mark.parametrize("algo", COMPLETE)
def test_star_topology(algo):
    """A hub with 6 leaves: the pseudo-tree is one level deep and wide
    (DPOP separator stress), the chain walk is hub-first or hub-last."""
    rng = np.random.default_rng(3)
    mats = {
        (0, j): rng.integers(0, 9, (3, 2)).tolist() for j in range(1, 7)
    }
    dcop = binary_dcop(mats, dom_sizes={0: 3})
    res = solve_result(dcop, algo)
    _, bf_cost = brute_force(dcop)
    assert res.cost == bf_cost
    # the reported assignment must itself achieve the reported cost
    assert dcop.solution_cost(res.assignment, 10000000)[1] == bf_cost


@pytest.mark.parametrize("algo", COMPLETE)
def test_hard_infeasible_csp_returns_min_violation(algo):
    """Every assignment violates at least one pseudo-hard constraint
    (10000 penalty): the exact algorithms must return an assignment with
    the FEWEST violations.  Metrics semantics (reference
    global_metrics): entries at/above the infinity threshold count as
    `violation`, not as cost — so the optimum here is violation=1 with
    the satisfiable constraint satisfied (cost 0)."""
    never = [[10000, 10000], [10000, 10000]]
    diff = [[10000, 0], [0, 10000]]
    mats = {(0, 1): never, (1, 2): diff}
    dcop = binary_dcop(mats)
    res = solve_result(dcop, algo)
    assert res.violation == 1  # the unsatisfiable constraint only
    assert res.cost == 0.0     # the diff constraint IS satisfied
    assert res.assignment["v1"] != res.assignment["v2"]


@pytest.mark.parametrize("algo", COMPLETE)
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_random_mixed_domains_vs_bruteforce(algo, seed):
    """Randomized graphs with ragged domain sizes (2-4), cross-checked
    against brute force — the padding paths of all three engines."""
    rng = np.random.default_rng(seed)
    n = 7
    sizes = {i: int(rng.integers(2, 5)) for i in range(n)}
    mats = {}
    for i in range(1, n):
        p = int(rng.integers(0, i))
        mats[(p, i)] = rng.integers(0, 10, (sizes[p], sizes[i])).tolist()
    # a couple of extra (non-tree) edges
    for _ in range(2):
        i, j = sorted(rng.choice(n, 2, replace=False).tolist())
        if (i, j) not in mats:
            mats[(i, j)] = rng.integers(
                0, 10, (sizes[i], sizes[j])).tolist()
    dcop = binary_dcop(mats, dom_sizes=sizes)
    res = solve_result(dcop, algo)
    _, bf_cost = brute_force(dcop)
    assert res.cost == bf_cost
