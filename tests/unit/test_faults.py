"""Fault-injection harness + hardened checkpoint unit tests.

Covers runtime/faults.py (plan parsing, deterministic injection,
heartbeat/stall detection, checkpoint corruption helpers),
runtime/checkpoint.py hardening (atomic write, CRC rejection, version
gate, rotation) and the thread-mode orchestrator's fault/auto-resume
integration.  The real multi-process crash/watchdog path is exercised
in tests/api/test_api_process_faults.py.
"""
import json
import os

import numpy as np
import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime.checkpoint import (
    CHECKPOINT_VERSION,
    CheckpointManager,
    load_checkpoint,
    read_state_npz,
    save_checkpoint,
    write_state_npz,
)
from pydcop_tpu.runtime.faults import (
    KILL_EXIT_CODE,
    Fault,
    FaultPlan,
    HeartbeatWriter,
    RankFaultInjector,
    corrupt_checkpoint,
    stalled_ranks,
)

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def tuto():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


class TestFaultPlan:
    def test_yaml_roundtrip(self, tmp_path):
        plan_yaml = tmp_path / "plan.yaml"
        plan_yaml.write_text(
            "seed: 7\n"
            "faults:\n"
            "  - kind: kill_rank\n"
            "    rank: 1\n"
            "    cycle: 8\n"
            "  - kind: stall_rank\n"
            "    rank: 0\n"
            "    cycle: 4\n"
            "    duration: 30\n"
            "  - kind: kill_agent\n"
            "    agent: a3\n"
            "    cycle: 10\n"
            "  - kind: corrupt_checkpoint\n"
            "    attempt: 1\n"
        )
        plan = FaultPlan.from_yaml(str(plan_yaml))
        assert plan.seed == 7
        assert [f.kind for f in plan.faults] == [
            "kill_rank", "stall_rank", "kill_agent", "corrupt_checkpoint"
        ]
        assert plan.for_rank(1)[0].cycle == 8
        assert plan.for_rank(0)[0].duration == 30
        assert plan.agent_kills()[0].agent == "a3"
        assert plan.checkpoint_faults(attempt=1)
        assert not plan.checkpoint_faults(attempt=0)
        # env/json channel preserves everything, including attempt=None
        plan.faults[0].attempt = None
        again = FaultPlan.from_json(plan.to_json())
        assert again.faults[0].attempt is None
        assert again.faults[3].attempt == 1

    def test_validation(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            Fault(kind="explode")
        with pytest.raises(ValueError, match="rank"):
            Fault(kind="kill_rank")
        with pytest.raises(ValueError, match="duration"):
            Fault(kind="stall_rank", rank=0)
        with pytest.raises(ValueError, match="agent"):
            Fault(kind="kill_agent")
        with pytest.raises(ValueError, match="unknown fields"):
            FaultPlan.from_dict(
                {"faults": [{"kind": "kill_rank", "rank": 0,
                             "banana": 1}]}
            )
        with pytest.raises(ValueError, match="faults"):
            FaultPlan.from_dict({"seed": 1})


class TestFaultKindCatalog:
    """ISSUE 12 satellite: ONE fault-kind catalog.  KIND_FIELDS is the
    machine-readable half; the table in docs/resilience.rst
    ("Fault-kind catalog") is the human half; FaultPlan.validate()
    enforces it.  These pins catch the next PR that adds a kind
    without documenting it (or documents one it never wired up)."""

    #: a minimal valid spec per kind (required fields only)
    MINIMAL = {
        "kill_rank": {"rank": 0},
        "stall_rank": {"rank": 0, "duration": 1.0},
        "kill_agent": {"agent": "a1"},
        "corrupt_checkpoint": {},
        "truncate_checkpoint": {},
        "raise_in_step": {"jid": "job-000001"},
        "nan_lane": {},
        "torn_journal_write": {},
        "stall_tick": {"duration": 0.1},
        "corrupt_cache_entry": {},
        "edit_factor": {"constraint": "c1"},
        "remove_agent_burst": {"count": 2},
        "add_agent_burst": {"count": 1},
        "kill_replica": {"replica": 0},
        "stall_replica": {"replica": 1, "duration": 0.5},
        "partition_replica": {"replica": 0, "duration": 1.0},
        "kill_device": {"device": 3},
        "shrink_mesh": {"devices": 4},
        "corrupt_slab": {"operand": "bucket0"},
        "kill_process": {"replica": 0},
        "partition_socket": {"replica": 1, "duration": 1.0},
        "corrupt_artifact": {},
    }

    def _docs_section(self):
        path = os.path.join(os.path.dirname(__file__), "..", "..",
                            "docs", "resilience.rst")
        with open(path, encoding="utf-8") as f:
            text = f.read()
        start = text.index("Fault-kind catalog")
        end = text.index("Watchdog and backoff")
        return text[start:end]

    def test_catalog_covers_every_kind(self):
        from pydcop_tpu.runtime.faults import KIND_FIELDS, KINDS

        assert set(KIND_FIELDS) == set(KINDS)

    def test_minimal_specs_cover_every_kind(self):
        from pydcop_tpu.runtime.faults import KINDS

        assert set(self.MINIMAL) == set(KINDS)

    def test_every_kind_roundtrips_through_yaml(self, tmp_path):
        """Every documented kind, written as YAML with exactly its
        catalog fields, loads + validates + survives the env/json
        channel byte-for-byte."""
        import yaml

        from pydcop_tpu.runtime.faults import KINDS

        spec = {"seed": 3, "faults": [
            {"kind": k, "cycle": i, **self.MINIMAL[k]}
            for i, k in enumerate(sorted(KINDS))
        ]}
        p = tmp_path / "catalog.yaml"
        p.write_text(yaml.safe_dump(spec))
        plan = FaultPlan.from_yaml(str(p))  # from_yaml validates
        assert plan.validate() == sorted(KINDS)
        again = FaultPlan.from_json(plan.to_json())
        assert [f.to_dict() for f in again.faults] == \
               [f.to_dict() for f in plan.faults]

    def test_every_kind_documented_and_nothing_else(self):
        """The docs table names exactly the catalog's kinds, and every
        kind's row names every field the catalog allows for it."""
        import re

        from pydcop_tpu.runtime.faults import KIND_FIELDS, KINDS

        section = self._docs_section()
        documented = set(re.findall(r"``([a-z_]+)``", section)) & {
            *KINDS,
            # a doc token that LOOKS like a kind but is not one would
            # land here and fail the equality below
        }
        assert documented == set(KINDS), (
            "docs/resilience.rst fault-kind table out of sync with "
            "runtime.faults.KINDS"
        )
        rows = section.split("* - ``")
        for kind in KINDS:
            row = next(r for r in rows if r.startswith(kind + "``"))
            for field in KIND_FIELDS[kind]:
                assert f"``{field}``" in row, (
                    f"docs row for {kind} does not name its "
                    f"``{field}`` field"
                )

    def test_validate_rejects_misaddressed_fields(self):
        plan = FaultPlan(faults=[
            Fault(kind="stall_tick", duration=0.1, rank=3),
        ])
        with pytest.raises(ValueError, match="never consumes"):
            plan.validate()
        plan = FaultPlan(faults=[
            Fault(kind="kill_replica", replica=0, agent="a1"),
        ])
        with pytest.raises(ValueError, match="never consumes"):
            plan.validate()
        # duration on a kind that never reads it
        plan = FaultPlan(faults=[
            Fault(kind="kill_rank", rank=0, duration=2.0),
        ])
        with pytest.raises(ValueError, match="never consumes"):
            plan.validate()

    def test_from_yaml_validates(self, tmp_path):
        p = tmp_path / "bad.yaml"
        p.write_text(
            "faults:\n"
            "  - kind: stall_tick\n"
            "    duration: 0.5\n"
            "    rank: 1\n"
        )
        with pytest.raises(ValueError, match="never consumes"):
            FaultPlan.from_yaml(str(p))


class TestRankFaultInjector:
    def _plan(self, **kw):
        return FaultPlan(faults=[Fault(**kw)])

    def test_kill_fires_at_first_boundary_past_cycle(self):
        exits = []
        inj = RankFaultInjector(
            self._plan(kind="kill_rank", rank=2, cycle=8), rank=2,
            attempt=0, _exit=exits.append,
        )
        inj.at_cycle(5)
        assert not exits
        inj.at_cycle(10)  # first boundary >= 8
        assert exits == [KILL_EXIT_CODE]
        inj.at_cycle(15)  # fires once
        assert exits == [KILL_EXIT_CODE]

    def test_attempt_scoping(self):
        exits = []
        inj = RankFaultInjector(
            self._plan(kind="kill_rank", rank=0, cycle=2, attempt=0),
            rank=0, attempt=1, _exit=exits.append,
        )
        inj.at_cycle(10)
        assert not exits  # attempt-0 fault must not fire on attempt 1
        inj_any = RankFaultInjector(
            self._plan(kind="kill_rank", rank=0, cycle=2, attempt=None),
            rank=0, attempt=3, _exit=exits.append,
        )
        inj_any.at_cycle(10)
        assert exits == [KILL_EXIT_CODE]

    def test_other_ranks_untouched(self):
        exits = []
        inj = RankFaultInjector(
            self._plan(kind="kill_rank", rank=1, cycle=0), rank=0,
            attempt=0, _exit=exits.append,
        )
        inj.at_cycle(100)
        assert not exits

    def test_stall_uses_duration(self):
        stalls = []
        inj = RankFaultInjector(
            self._plan(kind="stall_rank", rank=0, cycle=4, duration=7.5),
            rank=0, attempt=0, _stall=stalls.append,
        )
        assert inj.next_cycle() == 4
        inj.at_cycle(4)
        assert stalls == [7.5]


class TestHeartbeats:
    def test_writer_touches_file(self, tmp_path):
        path = str(tmp_path / "rank0.hb")
        hb = HeartbeatWriter(path, interval=0.05)
        hb.start()
        try:
            assert os.path.exists(path)
        finally:
            hb.stop()

    def test_stalled_ranks_by_mtime(self, tmp_path):
        fresh = str(tmp_path / "r0.hb")
        stale = str(tmp_path / "r1.hb")
        for p in (fresh, stale):
            with open(p, "w"):
                pass
        old = os.stat(stale).st_mtime - 60
        os.utime(stale, (old, old))
        assert stalled_ranks({0: fresh, 1: stale}, stall_timeout=5) == [1]
        # a missing file is startup, not a stall
        assert stalled_ranks(
            {0: str(tmp_path / "nope.hb")}, stall_timeout=5) == []


class TestCorruption:
    def test_deterministic_damage(self, tmp_path):
        a, b = str(tmp_path / "a.bin"), str(tmp_path / "b.bin")
        payload = bytes(range(256)) * 64
        for p in (a, b):
            with open(p, "wb") as f:
                f.write(payload)
        corrupt_checkpoint(a, seed=5)
        corrupt_checkpoint(b, seed=5)
        assert open(a, "rb").read() == open(b, "rb").read()
        assert open(a, "rb").read() != payload

    def test_truncate_shrinks(self, tmp_path):
        p = str(tmp_path / "t.bin")
        with open(p, "wb") as f:
            f.write(b"x" * 10000)
        corrupt_checkpoint(p, seed=1, mode="truncate")
        assert 0 < os.path.getsize(p) < 10000


class TestHardenedContainer:
    def _write(self, path):
        arrays = {"leaf_0": np.arange(12, dtype=np.float32).reshape(3, 4),
                  "leaf_1": np.ones(5, dtype=np.int32)}
        write_state_npz(path, arrays, {"kind": "test", "cycle": 3})
        return arrays

    def test_roundtrip_with_crcs(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        arrays = self._write(p)
        meta, got = read_state_npz(p)
        assert meta["version"] == CHECKPOINT_VERSION
        assert set(meta["crc"]) == {"leaf_0", "leaf_1"}
        np.testing.assert_array_equal(got["leaf_0"], arrays["leaf_0"])
        # no temp residue from the atomic write
        assert [f for f in os.listdir(tmp_path)
                if f.startswith(".ck_tmp_")] == []

    def test_corrupted_rejected(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        self._write(p)
        corrupt_checkpoint(p, seed=3)
        with pytest.raises(ValueError,
                           match="checksum mismatch|unreadable"):
            read_state_npz(p)

    def test_truncated_rejected(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        self._write(p)
        corrupt_checkpoint(p, seed=3, mode="truncate")
        with pytest.raises(ValueError, match="unreadable or truncated"):
            read_state_npz(p)

    def test_future_version_rejected(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        np.savez(p, __meta__=json.dumps({"version": 99}),
                 leaf_0=np.zeros(3))
        with pytest.raises(ValueError, match="schema version 99"):
            read_state_npz(p)

    def test_v1_files_still_load(self, tmp_path):
        # the original unversioned format: no version, no CRC table
        p = str(tmp_path / "v1.npz")
        np.savez(p, __meta__=json.dumps({"n_leaves": 1}),
                 leaf_0=np.arange(3))
        meta, arrays = read_state_npz(p)
        assert meta["n_leaves"] == 1

    def test_not_a_checkpoint_rejected(self, tmp_path):
        p = str(tmp_path / "foreign.npz")
        np.savez(p, x=np.zeros(3))
        with pytest.raises(ValueError, match="no __meta__"):
            read_state_npz(p)


class TestCheckpointManager:
    def test_rotation_keeps_newest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=2)
        for cycle in (5, 10, 15, 20):
            mgr.save_state(cycle, {"leaf_0": np.full(3, cycle)},
                           {"kind": "t"})
        cycles = [c for c, _ in mgr.snapshots()]
        assert cycles == [20, 15]

    def test_latest_valid_skips_corrupt(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep=3)
        for cycle in (5, 10):
            mgr.save_state(cycle, {"leaf_0": np.full(8, cycle,
                                                     np.float32)},
                           {"kind": "t"})
        corrupt_checkpoint(mgr.path_for(10), seed=0)
        got = mgr.latest_valid_state()
        assert got is not None
        cycle, meta, arrays = got
        assert cycle == 5
        np.testing.assert_array_equal(arrays["leaf_0"], np.full(8, 5))

    def test_all_corrupt_is_none(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save_state(5, {"leaf_0": np.zeros(64, np.float32)},
                       {"kind": "t"})
        corrupt_checkpoint(mgr.path_for(5), seed=0, mode="truncate")
        assert mgr.latest_valid_state() is None


class TestSolverCheckpointHardening:
    def test_corrupt_solver_checkpoint_rejected(self, tuto, tmp_path):
        """Acceptance: a deliberately damaged checkpoint is rejected by
        load_checkpoint with a clear ValueError, never loaded."""
        from pydcop_tpu.algorithms.maxsum import build_solver

        solver = build_solver(tuto)
        solver.run(cycles=4)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, solver)
        corrupt_checkpoint(path, seed=11)
        fresh = build_solver(tuto)
        with pytest.raises(ValueError,
                           match="checksum mismatch|unreadable"):
            load_checkpoint(path, fresh)
        assert getattr(fresh, "_last_state", None) is None

    def test_truncated_solver_checkpoint_rejected(self, tuto, tmp_path):
        from pydcop_tpu.algorithms.maxsum import build_solver

        solver = build_solver(tuto)
        solver.run(cycles=4)
        path = str(tmp_path / "ck.npz")
        save_checkpoint(path, solver)
        corrupt_checkpoint(path, seed=2, mode="truncate")
        with pytest.raises(ValueError, match="unreadable or truncated"):
            load_checkpoint(path, build_solver(tuto))


class TestOrchestratorFaults:
    def test_kill_agent_fault_routes_through_repair(self, tuto):
        from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

        victim = sorted(tuto.agents)[0]
        plan = FaultPlan(
            faults=[Fault(kind="kill_agent", agent=victim, cycle=10)]
        )
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc",
                                   fault_plan=plan)
        orch.deploy_computations()
        orch.start_replication(2)
        res = orch.run(cycles=20)
        m = orch.end_metrics()
        assert res.status == "FINISHED"
        assert res.cycle == 20  # the kill split, not shortened, the run
        assert victim not in orch.dcop.agents
        assert m["resilience"]["faults_injected"] == 1
        assert m["resilience"]["repairs"] == 1
        assert victim not in m["distribution"]
        # every computation survived the failure, re-hosted elsewhere
        hosted = [c for a in m["distribution"]
                  for c in m["distribution"][a]]
        assert sorted(hosted) == sorted(
            n.name for n in orch.cg.nodes)

    def test_checkpoint_and_auto_resume(self, tuto, tmp_path):
        from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

        d = str(tmp_path)
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc",
                                   checkpoint_dir=d, checkpoint_every=5)
        orch.deploy_computations()
        res = orch.run(cycles=12)
        assert orch.end_metrics()["resilience"]["checkpoints_saved"] >= 1
        assert CheckpointManager(d).latest()[0] == 12

        # a fresh orchestrator resumes exactly where the run ended —
        # 8 more cycles land on the same state as one 20-cycle run
        orch2 = VirtualOrchestrator(
            load_same(tuto), "maxsum", distribution="adhoc",
            checkpoint_dir=d, auto_resume=True,
        )
        orch2.deploy_computations()
        res2 = orch2.run(cycles=8)
        m2 = orch2.end_metrics()
        assert m2["resilience"]["resumes"] == 1
        assert res2.cycle == 20

        straight = VirtualOrchestrator(load_same(tuto), "maxsum",
                                       distribution="adhoc")
        straight.deploy_computations()
        res_straight = straight.run(cycles=20)
        assert res2.assignment == res_straight.assignment
        assert res2.cost == res_straight.cost

    def test_auto_resume_survives_corrupt_snapshot(self, tuto, tmp_path):
        from pydcop_tpu.runtime.orchestrator import VirtualOrchestrator

        d = str(tmp_path)
        orch = VirtualOrchestrator(tuto, "maxsum", distribution="adhoc",
                                   checkpoint_dir=d, checkpoint_every=5)
        orch.deploy_computations()
        orch.run(cycles=10)
        # newest snapshot corrupted: resume falls back to an older one
        newest = CheckpointManager(d).latest()[1]
        corrupt_checkpoint(newest, seed=4)
        orch2 = VirtualOrchestrator(
            load_same(tuto), "maxsum", distribution="adhoc",
            checkpoint_dir=d, auto_resume=True,
        )
        orch2.deploy_computations()
        res = orch2.run(cycles=5)
        assert res.status == "FINISHED"
        assert orch2.end_metrics()["resilience"]["resumes"] == 1
        assert res.cycle < 15  # resumed from an OLDER cycle than 10


def load_same(dcop):
    """Fresh copy of the tuto instance (orchestrators mutate agents)."""
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


class TestSolveResultCheckpointing:
    def test_solve_checkpoint_then_resume(self, tuto, tmp_path):
        from pydcop_tpu.runtime import solve_result

        d = str(tmp_path)
        res = solve_result(tuto, "maxsum", cycles=10,
                           checkpoint_dir=d, checkpoint_every=4)
        assert res.status == "FINISHED"
        assert CheckpointManager(d).latest()[0] == 10
        res2 = solve_result(load_same(tuto), "maxsum", cycles=20,
                            checkpoint_dir=d, checkpoint_every=4,
                            resume=True)
        assert res2.cycle == 20
        straight = solve_result(load_same(tuto), "maxsum", cycles=20)
        assert res2.assignment == straight.assignment

    def test_placement_path_rejects_checkpointing(self, tuto, tmp_path):
        from pydcop_tpu.distribution.objects import Distribution
        from pydcop_tpu.runtime import solve_result

        dist = Distribution({a: [] for a in tuto.agents})
        with pytest.raises(ValueError, match="not supported"):
            solve_result(tuto, "maxsum", distribution=dist,
                         checkpoint_dir=str(tmp_path))


class TestMeshContinuationValidation:
    """Satellite (ADVICE r5): the packed engine silently dropped a
    mismatched ``r`` continuation arg; both engines must now reject
    foreign (q, r) state with a clear error."""

    def _tensors(self):
        from pydcop_tpu.generators import generate_graph_coloring
        from pydcop_tpu.ops.compile import compile_factor_graph

        return compile_factor_graph(generate_graph_coloring(
            n_variables=12, n_colors=3, n_edges=20, soft=True,
            n_agents=1, seed=3,
        ))

    def test_generic_rejects_foreign_state(self):
        import jax.numpy as jnp

        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        solver = ShardedMaxSum(self._tensors(), build_mesh(4),
                               damping=0.5)
        _v, q, r = solver.run(cycles=2)
        bad = jnp.zeros((3, 3), dtype=jnp.float32)
        with pytest.raises(ValueError, match="continuation state"):
            solver.run(cycles=2, q=bad, r=r)
        with pytest.raises(ValueError, match="continuation state"):
            solver.run(cycles=2, q=q, r=bad)
        # a tuple (packed-engine state) into the generic engine
        with pytest.raises(ValueError, match="different engine"):
            solver.run(cycles=2, q=(q, q), r=r)

    def test_valid_continuation_still_works(self):
        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        solver = ShardedMaxSum(self._tensors(), build_mesh(4),
                               damping=0.5)
        v_full, _, _ = solver.run(cycles=6)
        _v, q, r = solver.run(cycles=3)
        v2, _, _ = solver.run(cycles=3, q=q, r=r)
        np.testing.assert_array_equal(v2, v_full)

    def test_state_host_roundtrip(self):
        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        t = self._tensors()
        solver = ShardedMaxSum(t, build_mesh(4), damping=0.5)
        v_full, _, _ = solver.run(cycles=6)
        _v, q, r = solver.run(cycles=3)
        host = solver.state_to_host(q, r)
        # a NEW engine (fresh process after a crash) restores the state
        solver2 = ShardedMaxSum(t, build_mesh(4), damping=0.5)
        q2, r2 = solver2.state_from_host(host)
        v2, _, _ = solver2.run(cycles=3, q=q2, r=r2)
        np.testing.assert_array_equal(v2, v_full)

    def test_state_from_host_rejects_mismatch(self):
        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        solver = ShardedMaxSum(self._tensors(), build_mesh(4),
                               damping=0.5)
        _v, q, r = solver.run(cycles=2)
        host = solver.state_to_host(q, r)
        host["leaf_0"] = host["leaf_0"][:-1]  # wrong shape
        with pytest.raises(ValueError, match="leaf shape|leaves"):
            solver.state_from_host(host)
