"""Elastic mesh + integrity tier (ISSUE 14).

The chaos matrix acceptance pin: for each of
``kill_device``/``shrink_mesh``/``corrupt_slab`` × {sharded maxsum
generic, sharded maxsum packed, sharded MGM, sharded DPOP}, the
injected run completes, and on the exact-restore path the final
assignment is bit-identical to the unfailed run.  ``corrupt_slab`` is
detected with zero false positives on clean runs.

The maxsum bit-identity pins ride the exact arithmetic tier
(docs/resilience.rst "Device loss and data integrity"): integer
costs, power-of-two domain sizes, damping 0.5 and a bounded cycle
count keep every message a small dyadic rational, so f32 addition is
associative and the trajectory is partition-independent.
"""
from __future__ import annotations

import numpy as np
import pytest

from pydcop_tpu.runtime.faults import Fault, FaultPlan
from pydcop_tpu.runtime.integrity import (
    SENTINEL_WIDTH,
    decode_sentinel,
    flip_bit,
    wrapsum_host,
)

CYCLES, CHUNK = 12, 4
LS_CYCLES, LS_CHUNK = 20, 5


@pytest.fixture(scope="module")
def exact_factor_tensors():
    """Ring coloring with integer costs and D=4 — the exact tier."""
    from pydcop_tpu.ops.compile import compile_binary_from_arrays

    V, D = 32, 4
    rng = np.random.default_rng(0)
    idx = np.arange(V)
    ei = np.concatenate([idx, idx])
    ej = np.concatenate([(idx + 1) % V, (idx + 2) % V])
    mats = rng.integers(0, 8, (2 * V, D, D)).astype(np.float32)
    unary = rng.integers(0, 4, (V, D)).astype(np.float32)
    return compile_binary_from_arrays(ei, ej, mats, V, unary=unary)


@pytest.fixture(scope="module")
def constraint_tensors():
    from pydcop_tpu.analysis.registry import _ring_constraint_tensors

    return _ring_constraint_tensors()


@pytest.fixture(scope="module")
def dpop_plan():
    from pydcop_tpu.generators import generate_graph_coloring
    from pydcop_tpu.graph import pseudotree
    from pydcop_tpu.ops.dpop_sweep import compile_sweep

    dcop = generate_graph_coloring(
        n_variables=12, n_colors=3, n_edges=16, soft=True,
        n_agents=1, seed=3,
    )
    tree = pseudotree.build_computation_graph(dcop)
    return compile_sweep(tree, dcop, "min")


def _runner(tensors, engine, plan=None, **kw):
    from pydcop_tpu.parallel.elastic import ElasticRunner

    kw.setdefault("sentinel", True)
    return ElasticRunner(tensors, engine=engine, fault_plan=plan,
                         **kw)


@pytest.fixture(scope="module")
def clean_maxsum(exact_factor_tensors):
    return _runner(exact_factor_tensors, "maxsum",
                   chunk=CHUNK).solve(CYCLES, seed=0)


@pytest.fixture(scope="module")
def clean_maxsum_packed(exact_factor_tensors):
    return _runner(exact_factor_tensors, "maxsum", chunk=CHUNK,
                   use_packed=True).solve(CYCLES, seed=0)


@pytest.fixture(scope="module")
def clean_mgm(constraint_tensors):
    return _runner(constraint_tensors, "mgm",
                   chunk=LS_CHUNK).solve(LS_CYCLES, seed=0)


@pytest.fixture(scope="module")
def clean_dpop(dpop_plan):
    from pydcop_tpu.parallel.elastic import ElasticDpop

    return ElasticDpop(dpop_plan).solve()


# ---------------------------------------------------------------------------
# integrity primitives


class TestIntegrityPrimitives:
    def test_wrapsum_device_host_agree(self):
        import jax
        import jax.numpy as jnp

        from pydcop_tpu.runtime.integrity import wrapsum_words

        rng = np.random.default_rng(1)
        a = rng.normal(size=(37, 5)).astype(np.float32)
        dev = int(jax.jit(wrapsum_words)(jnp.asarray(a)))
        assert dev == wrapsum_host([a])

    def test_wrapsum_is_layout_independent(self):
        rng = np.random.default_rng(2)
        a = rng.normal(size=(24,)).astype(np.float32)
        perm = rng.permutation(24)
        padded = np.concatenate(
            [a[perm], np.zeros(8, np.float32)]
        )
        assert wrapsum_host([a]) == wrapsum_host([padded])

    def test_flip_bit_is_seeded_and_single_bit(self):
        a = np.zeros((16, 4), np.float32)
        b1 = flip_bit(a, seed=5)
        b2 = flip_bit(a, seed=5)
        assert np.array_equal(b1, b2)
        diff = a.view(np.uint32) ^ b1.view(np.uint32)
        assert bin(int(diff.sum(dtype=np.uint64))).count("1") == 1

    def test_flip_bit_respects_shard_block(self):
        a = np.zeros((8, 4), np.float32)
        b = flip_bit(a, seed=1, shard=3, n_shards=4)
        rows = np.flatnonzero((a != b).any(axis=1))
        assert rows.size == 1 and 6 <= rows[0] < 8

    def test_decode_roundtrip(self):
        import jax.numpy as jnp

        v = jnp.asarray([3, 7, 11,
                         np.float32(0.5).view(np.int32)],
                        dtype=jnp.int32)
        r = decode_sentinel(v)
        assert (r.nonfinite, r.state_checksum,
                r.operand_checksum) == (3, 7, 11)
        assert r.residual == 0.5
        with pytest.raises(ValueError):
            decode_sentinel(np.zeros(3, np.int32))

    def test_trip_reasons(self):
        from pydcop_tpu.runtime.integrity import SentinelReading

        ok = SentinelReading(0, 1, 2, 0.0)
        assert ok.trip_reason(operand_ref=2) is None
        assert SentinelReading(1, 1, 2, 0.0).trip_reason() \
            == "nonfinite"
        assert SentinelReading(0, 1, 2, 5.0).trip_reason() \
            == "residual"
        assert SentinelReading(0, 1, 2, float("nan")).trip_reason() \
            == "residual"
        assert ok.trip_reason(operand_ref=9) == "operand"
        assert ok.trip_reason(operand_ref=None) is None

    def test_counters_schema(self):
        from pydcop_tpu.runtime.stats import IntegrityCounters

        c = IntegrityCounters()
        c.inc("sentinel_trips")
        assert c.any_faults
        with pytest.raises(KeyError):
            c.inc("nope")


# ---------------------------------------------------------------------------
# canonical codec


class TestCanonicalCodec:
    def test_roundtrip_across_meshes(self, exact_factor_tensors):
        import jax
        from jax.sharding import Mesh

        from pydcop_tpu.parallel.elastic import (
            canonical_messages,
            stacked_messages,
        )
        from pydcop_tpu.parallel.mesh import AXIS, ShardedMaxSum

        devs = jax.devices()
        e8 = ShardedMaxSum(exact_factor_tensors,
                           Mesh(np.array(devs), (AXIS,)),
                           use_packed=False)
        e5 = ShardedMaxSum(exact_factor_tensors,
                           Mesh(np.array(devs[:5]), (AXIS,)),
                           use_packed=False)
        rng = np.random.default_rng(3)
        E8 = int(np.asarray(e8.st.edge_var).shape[0])
        D = e8.st.max_domain_size
        # messages live on REAL edges; dummy rows are zero by contract
        stacked = np.zeros((E8, D), np.float32)
        real = np.asarray(e8.st.edge_var) < e8.st.n_vars
        stacked[real] = rng.normal(
            size=(int(real.sum()), D)
        ).astype(np.float32)
        canon = canonical_messages(e8, stacked)
        back = stacked_messages(e8, canon)
        assert np.array_equal(back, stacked)
        # cross-mesh transport preserves every real-edge message
        re5 = stacked_messages(e5, canon)
        assert np.array_equal(canonical_messages(e5, re5), canon)


# ---------------------------------------------------------------------------
# the chaos matrix (acceptance pin)


class TestChaosMatrix:
    # -- sharded maxsum, generic (exact-restore path: bitmatch) ----------

    def test_maxsum_clean_zero_false_positives(self, clean_maxsum):
        c = clean_maxsum.counters.counts
        assert c["sentinel_trips"] == 0
        assert c["scrub_mismatches"] == 0

    def test_maxsum_kill_device(self, exact_factor_tensors,
                                clean_maxsum):
        plan = FaultPlan(faults=[
            Fault(kind="kill_device", device=3, cycle=5),
        ], seed=7)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK).solve(CYCLES, seed=0)
        assert r.n_devices == clean_maxsum.n_devices - 1
        assert r.counters.counts["elastic_shrinks"] == 1
        assert r.counters.counts["devices_lost"] == 1
        assert np.array_equal(r.values, clean_maxsum.values)

    def test_maxsum_shrink_mesh(self, exact_factor_tensors,
                                clean_maxsum):
        plan = FaultPlan(faults=[
            Fault(kind="shrink_mesh", devices=5, cycle=6),
        ], seed=7)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK).solve(CYCLES, seed=0)
        assert r.n_devices == 5
        assert r.counters.counts["repartitions"] >= 2
        assert np.array_equal(r.values, clean_maxsum.values)

    def test_maxsum_corrupt_slab_operand(self, exact_factor_tensors,
                                         clean_maxsum):
        plan = FaultPlan(faults=[
            Fault(kind="corrupt_slab", operand="bucket0", cycle=4),
        ], seed=3)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK).solve(CYCLES, seed=0)
        c = r.counters.counts
        # detected within ONE chunk by the operand-checksum sentinel
        assert c["sentinel_trips"] == 1
        assert c["sdc_detected"] == 1
        assert c["detection_latency_chunks"] <= 1
        assert c["snapshot_restores"] == 1
        assert np.array_equal(r.values, clean_maxsum.values)

    def test_maxsum_corrupt_state_caught_by_scrub(
            self, exact_factor_tensors, clean_maxsum):
        plan = FaultPlan(faults=[
            Fault(kind="corrupt_slab", operand="q", cycle=4),
        ], seed=3)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK, scrub_every=1).solve(CYCLES, seed=0)
        c = r.counters.counts
        assert c["scrub_mismatches"] == 1
        assert c["sdc_detected"] == 1
        assert np.array_equal(r.values, clean_maxsum.values)

    def test_maxsum_below_floor_cold_repacks(
            self, exact_factor_tensors, clean_maxsum):
        """The ladder floor: shrinking under --elastic-min-devices
        takes ONE counted cold repack + replay instead of the warm
        shrink — and still bit-matches (exact tier)."""
        plan = FaultPlan(faults=[
            Fault(kind="shrink_mesh", devices=2, cycle=5),
        ], seed=7)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK, min_devices=4).solve(CYCLES, seed=0)
        c = r.counters.counts
        assert c["cold_repacks"] == 1
        assert c["elastic_shrinks"] == 0
        assert np.array_equal(r.values, clean_maxsum.values)

    # -- sharded maxsum, packed (cold-repack rung on shrink) -------------

    def test_packed_kill_device(self, exact_factor_tensors,
                                clean_maxsum_packed):
        plan = FaultPlan(faults=[
            Fault(kind="kill_device", device=2, cycle=5),
        ], seed=7)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK, use_packed=True).solve(CYCLES,
                                                        seed=0)
        c = r.counters.counts
        assert r.n_devices == clean_maxsum_packed.n_devices - 1
        assert c["cold_repacks"] == 1  # packed state is layout-bound
        # deterministic replay on the exact tier still bit-matches
        assert np.array_equal(r.values, clean_maxsum_packed.values)

    def test_packed_shrink_mesh(self, exact_factor_tensors,
                                clean_maxsum_packed):
        plan = FaultPlan(faults=[
            Fault(kind="shrink_mesh", devices=6, cycle=6),
        ], seed=7)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK, use_packed=True).solve(CYCLES,
                                                        seed=0)
        assert r.n_devices == 6
        assert np.array_equal(r.values, clean_maxsum_packed.values)

    def test_packed_corrupt_slab(self, exact_factor_tensors,
                                 clean_maxsum_packed):
        plan = FaultPlan(faults=[
            Fault(kind="corrupt_slab", operand="cost", cycle=4),
        ], seed=5)
        r = _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK, use_packed=True).solve(CYCLES,
                                                        seed=0)
        c = r.counters.counts
        assert c["sentinel_trips"] == 1
        assert c["sdc_detected"] == 1
        assert np.array_equal(r.values, clean_maxsum_packed.values)

    # -- sharded MGM (exact-restore path: bitmatch) ----------------------

    def test_mgm_clean_zero_false_positives(self, clean_mgm):
        c = clean_mgm.counters.counts
        assert c["sentinel_trips"] == 0
        assert c["scrub_mismatches"] == 0

    def test_mgm_kill_device(self, constraint_tensors, clean_mgm):
        plan = FaultPlan(faults=[
            Fault(kind="kill_device", device=1, cycle=7),
        ], seed=1)
        r = _runner(constraint_tensors, "mgm", plan,
                    chunk=LS_CHUNK).solve(LS_CYCLES, seed=0)
        assert r.counters.counts["elastic_shrinks"] == 1
        assert np.array_equal(r.values, clean_mgm.values)

    def test_mgm_shrink_mesh(self, constraint_tensors, clean_mgm):
        plan = FaultPlan(faults=[
            Fault(kind="shrink_mesh", devices=4, cycle=11),
        ], seed=1)
        r = _runner(constraint_tensors, "mgm", plan,
                    chunk=LS_CHUNK).solve(LS_CYCLES, seed=0)
        assert r.n_devices == 4
        assert np.array_equal(r.values, clean_mgm.values)

    def test_mgm_corrupt_slab(self, constraint_tensors, clean_mgm):
        plan = FaultPlan(faults=[
            Fault(kind="corrupt_slab", operand="bucket0", cycle=5),
        ], seed=2)
        r = _runner(constraint_tensors, "mgm", plan,
                    chunk=LS_CHUNK).solve(LS_CYCLES, seed=0)
        c = r.counters.counts
        assert c["sentinel_trips"] == 1
        assert c["sdc_detected"] == 1
        assert c["detection_latency_chunks"] <= 1
        assert np.array_equal(r.values, clean_mgm.values)

    # -- sharded DPOP (one-shot sweep) -----------------------------------

    def test_dpop_clean_zero_false_positives(self, clean_dpop):
        assert clean_dpop.counters.counts["scrub_mismatches"] == 0

    def test_dpop_kill_device(self, dpop_plan, clean_dpop):
        from pydcop_tpu.parallel.elastic import ElasticDpop

        plan = FaultPlan(faults=[
            Fault(kind="kill_device", device=5, cycle=0),
        ], seed=1)
        r = ElasticDpop(dpop_plan, fault_plan=plan).solve()
        assert r.n_devices == clean_dpop.n_devices - 1
        assert np.array_equal(r.values, clean_dpop.values)

    def test_dpop_shrink_mesh(self, dpop_plan, clean_dpop):
        from pydcop_tpu.parallel.elastic import ElasticDpop

        plan = FaultPlan(faults=[
            Fault(kind="shrink_mesh", devices=4, cycle=0),
        ], seed=1)
        r = ElasticDpop(dpop_plan, fault_plan=plan).solve()
        assert r.n_devices == 4
        assert np.array_equal(r.values, clean_dpop.values)

    def test_dpop_corrupt_slab(self, dpop_plan, clean_dpop):
        from pydcop_tpu.parallel.elastic import ElasticDpop

        plan = FaultPlan(faults=[
            Fault(kind="corrupt_slab", operand="local", cycle=0),
        ], seed=2)
        r = ElasticDpop(dpop_plan, fault_plan=plan).solve()
        c = r.counters.counts
        assert c["scrub_mismatches"] == 1
        assert c["sdc_detected"] == 1
        assert c["snapshot_restores"] == 1
        assert np.array_equal(r.values, clean_dpop.values)


# ---------------------------------------------------------------------------
# sentinel plumbing on the engines


class TestSentinelPlumbing:
    def test_sentinel_rides_values_tensor(self, exact_factor_tensors):
        """One tensor per chunk: [V] values ++ int32[4] sentinel."""
        import jax

        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        eng = ShardedMaxSum(exact_factor_tensors, build_mesh(),
                            use_packed=False, sentinel=True)
        v, q, r = eng.run(cycles=2, seed=0)
        assert v.shape == (exact_factor_tensors.n_vars,)
        assert eng.last_sentinel.shape == (SENTINEL_WIDTH,)
        reading = decode_sentinel(eng.last_sentinel)
        assert reading.nonfinite == 0
        # operand checksum matches the host reference exactly
        ref = wrapsum_host([
            np.asarray(eng.get_operand(n))
            for n in eng.operand_names()
        ])
        assert reading.operand_checksum == ref
        del jax  # imported for parity with other engines' tests

    def test_sentinel_does_not_perturb_values(
            self, exact_factor_tensors):
        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        a = ShardedMaxSum(exact_factor_tensors, build_mesh(),
                          use_packed=False, sentinel=True)
        b = ShardedMaxSum(exact_factor_tensors, build_mesh(),
                          use_packed=False, sentinel=False)
        va, *_ = a.run(cycles=3, seed=0)
        vb, *_ = b.run(cycles=3, seed=0)
        assert np.array_equal(va, vb)

    def test_state_checksum_is_partition_independent(
            self, exact_factor_tensors):
        """The layout-independence claim the scrub rests on: dense vs
        boundary-compacted layouts produce the SAME state checksum."""
        from pydcop_tpu.parallel.mesh import ShardedMaxSum, build_mesh

        readings = []
        for overlap in ("off", "exact"):
            e = ShardedMaxSum(exact_factor_tensors, build_mesh(),
                              use_packed=False, overlap=overlap,
                              sentinel=True)
            e.run(cycles=3, seed=0)
            readings.append(decode_sentinel(e.last_sentinel))
        assert (readings[0].state_checksum
                == readings[1].state_checksum)

    def test_ls_sentinel_requires_generic_dense(
            self, constraint_tensors):
        from pydcop_tpu.parallel.mesh import (
            ShardedLocalSearch,
            build_mesh,
        )

        with pytest.raises(ValueError, match="generic dense"):
            ShardedLocalSearch(constraint_tensors, build_mesh(),
                               rule="mgm", use_packed=False,
                               overlap="exact", sentinel=True)

    def test_mgm_chunked_equals_unchunked(self, constraint_tensors):
        from pydcop_tpu.parallel.mesh import (
            ShardedLocalSearch,
            build_mesh,
        )

        whole = ShardedLocalSearch(constraint_tensors, build_mesh(),
                                   rule="mgm", use_packed=False,
                                   overlap="off")
        v_whole = whole.run(cycles=10, seed=0)
        chunked = ShardedLocalSearch(constraint_tensors, build_mesh(),
                                     rule="mgm", use_packed=False,
                                     overlap="off")
        vals, x, aux = chunked.run_chunked(4, seed=0, epoch=0)
        vals, x, aux = chunked.run_chunked(6, x=x, aux=aux, seed=0,
                                           epoch=1)
        assert np.array_equal(vals, v_whole)


# ---------------------------------------------------------------------------
# events + fleet capacity advertising


class TestEventsAndFleet:
    def test_integrity_and_elastic_events_emitted(
            self, exact_factor_tensors):
        from pydcop_tpu.runtime.events import event_bus

        seen = []
        cb = lambda topic, evt: seen.append(topic)  # noqa: E731
        event_bus.enabled = True
        event_bus.subscribe("integrity.*", cb)
        event_bus.subscribe("elastic.*", cb)
        try:
            plan = FaultPlan(faults=[
                Fault(kind="corrupt_slab", operand="bucket0",
                      cycle=4),
                Fault(kind="kill_device", device=1, cycle=9),
            ], seed=3)
            _runner(exact_factor_tensors, "maxsum", plan,
                    chunk=CHUNK).solve(CYCLES, seed=0)
        finally:
            event_bus.unsubscribe(cb)
            event_bus.enabled = False
        assert "integrity.injected" in seen
        assert "integrity.sentinel.trip" in seen
        assert "integrity.restore" in seen
        assert "elastic.device.lost" in seen
        assert "elastic.shrink" in seen
        assert "elastic.resumed" in seen

    def test_router_capacity_scales_placement(self):
        from pydcop_tpu.serve.router import FleetRouter

        router = FleetRouter()
        router.add_replica("a")
        router.add_replica("b")
        router.set_capacity("a", 0.25)
        # a at quarter capacity with 1 job is "heavier" than b with 3
        router.job_placed("a")
        for _ in range(3):
            router.job_placed("b")
        name, _warm = router.place(("mgm", (), "x", (2,)))
        assert name == "b"
        assert router.stats()["a"]["capacity"] == 0.25

    def test_fleet_kill_device_advertises_capacity(self, tmp_path):
        from pydcop_tpu.serve.fleet import SolveFleet

        plan = FaultPlan(faults=[
            Fault(kind="kill_device", device=0, replica=1, cycle=0),
        ], seed=1)
        fleet = SolveFleet(
            replicas=2, lanes=1, fault_plan=plan,
            journal_dir=str(tmp_path), devices_per_replica=4,
        )
        try:
            f = plan.fleet_faults()[0]
            fleet._inject("kill_device", f, 0.0)
            stats = fleet.router.stats()
            assert stats["replica-1"]["capacity"] == 0.75
            assert fleet.counters.counts["devices_lost"] == 1
            assert fleet.counters.counts["capacity_reduced"] == 1
            # placement drains toward the whole replica under equal
            # load pressure
            fleet.router.job_placed("replica-0")
            fleet.router.job_placed("replica-1")
            name, _w = fleet.router.place(("mgm", (), "x", (2,)))
            assert name == "replica-0"
        finally:
            fleet.stop(drain=False)

    def test_twin_chaos_plan_carries_device_fault(self):
        from pydcop_tpu.scenario.twin import default_chaos_plan

        plan = default_chaos_plan()
        kinds = plan.validate()
        assert "kill_device" in kinds
        # replica-scoped: consumed by the FLEET, not the elastic tier
        assert not plan.device_faults()
        assert any(f.kind == "kill_device"
                   for f in plan.fleet_faults())
