"""Unit tests for the distribution (placement) layer."""
import os

import pytest

from pydcop_tpu.dcop import AgentDef, load_dcop_from_file
from pydcop_tpu.dcop.yamldcop import DistributionHints
from pydcop_tpu.distribution import (
    ImpossibleDistributionException,
    list_available_distributions,
    load_distribution_module,
)
from pydcop_tpu.graph import constraints_hypergraph, factor_graph

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")

GREEDY = ["oneagent", "adhoc", "gh_cgdp", "heur_comhost", "gh_secp_cgdp",
          "gh_secp_fgdp"]
ILP = ["ilp_fgdp", "ilp_compref", "ilp_compref_fg", "oilp_cgdp"]
# the optimal SECP ILPs degenerate on non-SECP instances (see
# test_oilp_secp_degenerate_on_non_secp) and are covered on a real SECP
# instance in test_distribution_secp.py
ILP_SECP = ["oilp_secp_cgdp", "oilp_secp_fgdp"]


@pytest.fixture
def tuto():
    dcop = load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )
    cg = constraints_hypergraph.build_computation_graph(dcop)
    return dcop, cg


def _mem(node):
    return 1.0


def _load(node, target=None):
    return 1.0


def test_registry():
    mods = list_available_distributions()
    for m in GREEDY + ILP + ILP_SECP + ["yamlformat"]:
        if m == "yamlformat":
            assert m not in mods  # excluded (not a strategy)
        else:
            assert m in mods, m


@pytest.mark.parametrize("name", GREEDY + ILP)
def test_distribute_all_hosted(tuto, name):
    dcop, cg = tuto
    mod = load_distribution_module(name)
    dist = mod.distribute(
        cg, dcop.agents.values(), hints=None,
        computation_memory=_mem, communication_load=_load,
    )
    hosted = sorted(dist.computations)
    assert hosted == sorted(n.name for n in cg.nodes)
    # capacity respected (all capacities are 100 here)
    for a in dist.agents:
        assert len(dist.computations_hosted(a)) <= 100


def test_oneagent_needs_enough_agents(tuto):
    dcop, cg = tuto
    mod = load_distribution_module("oneagent")
    few = [AgentDef("only_one")]
    with pytest.raises(ImpossibleDistributionException):
        mod.distribute(cg, few)


@pytest.mark.parametrize("name", ["adhoc", "gh_cgdp", "ilp_compref"])
def test_must_host_hints(tuto, name):
    dcop, cg = tuto
    mod = load_distribution_module(name)
    hints = DistributionHints(must_host={"a1": ["v1"], "a2": ["v2"]})
    dist = mod.distribute(
        cg, dcop.agents.values(), hints=hints,
        computation_memory=_mem, communication_load=_load,
    )
    assert "v1" in dist.computations_hosted("a1")
    assert "v2" in dist.computations_hosted("a2")


def test_capacity_limits():
    from pydcop_tpu.dcop import DCOP, Domain, Variable, constraint_from_str

    d = Domain("d", "d", [0, 1])
    dcop = DCOP("t")
    vs = [Variable(f"v{i}", d) for i in range(4)]
    for i in range(3):
        dcop.add_constraint(
            constraint_from_str(f"c{i}", f"v{i} + v{i+1}", vs))
    cg = constraints_hypergraph.build_computation_graph(dcop)
    # capacity 2 per agent, 4 computations of size 1 → >= 2 agents needed
    agents = [AgentDef("a1", capacity=2), AgentDef("a2", capacity=2)]
    for name in ("adhoc", "gh_cgdp", "ilp_compref"):
        mod = load_distribution_module(name)
        dist = mod.distribute(
            cg, agents, computation_memory=_mem, communication_load=_load
        )
        for a in dist.agents:
            assert len(dist.computations_hosted(a)) <= 2

    # impossible: capacity 1 on one agent only
    tiny = [AgentDef("a1", capacity=1)]
    for name in ("adhoc", "gh_cgdp"):
        mod = load_distribution_module(name)
        with pytest.raises(ImpossibleDistributionException):
            mod.distribute(cg, tiny, computation_memory=_mem)


def test_ilp_optimal_communication(tuto):
    """The ILP must achieve communication cost <= any greedy placement."""
    dcop, cg = tuto
    from pydcop_tpu.distribution._costs import distribution_cost

    ilp = load_distribution_module("ilp_fgdp").distribute(
        cg, dcop.agents.values(), computation_memory=_mem,
        communication_load=_load,
    )
    greedy = load_distribution_module("adhoc").distribute(
        cg, dcop.agents.values(), computation_memory=_mem,
        communication_load=_load,
    )
    _, ilp_comm, _ = distribution_cost(
        ilp, cg, dcop.agents.values(), _mem, _load)
    _, greedy_comm, _ = distribution_cost(
        greedy, cg, dcop.agents.values(), _mem, _load)
    assert ilp_comm <= greedy_comm + 1e-6


def test_factor_graph_distribution(tuto):
    dcop, _ = tuto
    fg = factor_graph.build_computation_graph(dcop)
    dist = load_distribution_module("ilp_compref_fg").distribute(
        fg, dcop.agents.values(), computation_memory=_mem,
        communication_load=_load,
    )
    assert sorted(dist.computations) == sorted(n.name for n in fg.nodes)


def test_yamlformat_roundtrip(tuto):
    dcop, cg = tuto
    from pydcop_tpu.distribution import yamlformat

    dist = load_distribution_module("adhoc").distribute(
        cg, dcop.agents.values(), computation_memory=_mem,
    )
    dumped = yamlformat.yaml_dist(dist)
    dist2 = yamlformat.load_dist(dumped)
    assert dist2 == dist


@pytest.mark.parametrize("name", ["oilp_secp_cgdp", "oilp_secp_fgdp"])
def test_oilp_secp_degenerate_on_non_secp(tuto, name):
    """On a non-SECP instance every computation has hosting_cost 0 on the
    first agent, so actuator pre-assignment pins everything there and the
    liveness constraints (every empty agent hosts >= 1, reference
    oilp_secp_cgdp.py:206-214) become infeasible — the reference raises
    ImpossibleDistributionException (oilp_secp_cgdp.py:280-281), and so
    do we (ADVICE r2)."""
    dcop, cg = tuto
    mod = load_distribution_module(name)
    with pytest.raises(ImpossibleDistributionException):
        mod.distribute(
            cg, dcop.agents.values(), hints=None,
            computation_memory=_mem, communication_load=_load,
        )
