"""Serialized runner artifacts (pydcop_tpu.serve.artifacts).

The zero-compile bring-up layer, pinned without spawning processes:

* a compiled runner round-trips through ``serialize_executable`` +
  the store and still computes the SAME outputs;
* version/ABI pinning: a different format version or a different
  jax/jaxlib/backend tag is a **stale** refusal — never deserialized;
* corruption (flipped blob byte, truncated file, garbage header) is a
  **corrupt** refusal caught by CRC/structure checks — never
  deserialized, counted, recompiled;
* the compile cache counts an artifact load as ``artifact_hits``
  (NOT a miss) — the cold-join acceptance pin ``misses == 0`` reads
  straight off these counters.
"""
import itertools
import json
import os
import time

import jax.numpy as jnp
import numpy as np
import pytest

from pydcop_tpu.batch.cache import CompileCache
from pydcop_tpu.serve.artifacts import (
    ARTIFACT_FORMAT,
    AotRunner,
    ArtifactStore,
    _serialize_compiled,
    abi_tag,
    artifact_name,
    corrupt_artifact_file,
)

KEY = ("dsa", "p=1", ((3, 4), (2,)), 8, 7)


_salt = itertools.count(time.time_ns() % (1 << 30))


@pytest.fixture(autouse=True)
def _no_persistent_xla_cache():
    """Compile with the persistent XLA cache OFF, exactly as an
    exporting replica does (serve/procfleet.py ReplicaWorker): with
    the cache engaged, the second and later same-shaped compiles in a
    process serialize into payloads missing their deduplicated kernel
    symbols ("Symbols not found: broadcast_add_fusion.1") and cannot
    be loaded back.  ``config.update(None)`` alone is not enough once
    the cache singleton is memoized — it must also be reset."""
    import jax

    try:
        from jax._src import compilation_cache as cc
    except ImportError:  # pragma: no cover - older/newer layout
        cc = None
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    if cc is not None:
        cc.reset_cache()
    yield
    jax.config.update("jax_compilation_cache_dir", prev)
    if cc is not None:
        cc.reset_cache()


def _aot_runner():
    """A tiny compiled function shaped like a bucket runner call.

    Each call bakes a fresh constant into the function so the compile
    is always a real compile; tests only compare a runner against its
    own loaded copy, so the constant value is irrelevant."""
    import jax

    salt = float(next(_salt))

    def fn(arrays, state, xs, n_active, done_mask):
        return (arrays * 2 + state) * 0 + salt, xs + n_active, done_mask

    args = (jnp.arange(4.0), jnp.ones(4), jnp.zeros(3),
            jnp.int32(2), jnp.zeros(3, dtype=bool))
    compiled = jax.jit(fn).lower(*args).compile()
    return AotRunner(compiled, _serialize_compiled(compiled)), args


class TestStoreRoundtrip:
    def test_save_load_same_outputs(self, tmp_path):
        runner, args = _aot_runner()
        store = ArtifactStore(str(tmp_path))
        path = store.save(KEY, runner)
        assert path and os.path.exists(path)
        loaded = ArtifactStore(str(tmp_path)).load(KEY)
        assert loaded is not None
        a, b, c = runner(*args)
        a2, b2, c2 = loaded(*args)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(a2))
        np.testing.assert_array_equal(np.asarray(b), np.asarray(b2))
        np.testing.assert_array_equal(np.asarray(c), np.asarray(c2))

    def test_plain_miss_counts_miss(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        assert store.load(KEY) is None
        assert store.stats()["misses"] == 1

    def test_runner_without_triple_not_exported(self, tmp_path):
        store = ArtifactStore(str(tmp_path))
        assert store.save(KEY, lambda *a: None) is None
        assert store.stats()["entries"] == 0

    def test_name_is_stable(self):
        assert artifact_name(KEY) == artifact_name(KEY)
        assert artifact_name(KEY) != artifact_name(KEY[:-1] + (8,))


class TestRejections:
    def _saved(self, tmp_path):
        runner, _args = _aot_runner()
        store = ArtifactStore(str(tmp_path))
        path = store.save(KEY, runner)
        return store, path

    def test_corrupt_blob_rejected_loudly(self, tmp_path, caplog):
        _store, path = self._saved(tmp_path)
        assert corrupt_artifact_file(path, seed=3)
        fresh = ArtifactStore(str(tmp_path))
        with caplog.at_level("WARNING"):
            assert fresh.load(KEY) is None
        assert fresh.stats()["rejected_corrupt"] == 1
        assert any("CORRUPT" in r.message for r in caplog.records)

    def test_truncated_file_rejected(self, tmp_path):
        _store, path = self._saved(tmp_path)
        raw = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(raw[: len(raw) // 2])
        fresh = ArtifactStore(str(tmp_path))
        assert fresh.load(KEY) is None
        assert fresh.stats()["rejected_corrupt"] == 1

    def test_stale_format_version_refused(self, tmp_path, caplog):
        _store, path = self._saved(tmp_path)
        raw = open(path, "rb").read()
        nl = raw.find(b"\n")
        header = json.loads(raw[:nl])
        header["format"] = ARTIFACT_FORMAT + 1
        with open(path, "wb") as f:
            f.write(json.dumps(header, sort_keys=True).encode()
                    + b"\n" + raw[nl + 1:])
        fresh = ArtifactStore(str(tmp_path))
        with caplog.at_level("WARNING"):
            assert fresh.load(KEY) is None
        assert fresh.stats()["rejected_stale"] == 1
        assert any("STALE" in r.message for r in caplog.records)

    def test_stale_abi_refused(self, tmp_path):
        """An artifact from a different jax/jaxlib/backend must not
        even be unpickled here — serialized executables are
        machine-specific."""
        _store, path = self._saved(tmp_path)
        raw = open(path, "rb").read()
        nl = raw.find(b"\n")
        header = json.loads(raw[:nl])
        header["abi"] = dict(header["abi"], jax="0.0.1-elsewhere")
        with open(path, "wb") as f:
            f.write(json.dumps(header, sort_keys=True).encode()
                    + b"\n" + raw[nl + 1:])
        fresh = ArtifactStore(str(tmp_path))
        assert fresh.load(KEY) is None
        assert fresh.stats()["rejected_stale"] == 1

    def test_recompile_overwrites_bad_artifact(self, tmp_path):
        _store, path = self._saved(tmp_path)
        corrupt_artifact_file(path)
        fresh = ArtifactStore(str(tmp_path))
        assert fresh.load(KEY) is None
        runner, _args = _aot_runner()
        assert fresh.save(KEY, runner) == path
        assert fresh.load(KEY) is not None

    def test_abi_tag_shape(self):
        tag = abi_tag()
        assert set(tag) == {"jax", "jaxlib", "backend"}


class TestCacheIntegration:
    def test_artifact_hit_is_not_a_miss(self, tmp_path):
        """The cold-join pin's arithmetic: a peer's exported runner
        loads with misses == 0 and artifact_hits == entries."""
        runner, _args = _aot_runner()
        ArtifactStore(str(tmp_path)).save(KEY, runner)

        cold = CompileCache(artifacts=ArtifactStore(str(tmp_path)))
        fn, was_hit = cold.get_or_build(
            KEY, builder=lambda: pytest.fail("must not compile")
        )
        assert was_hit
        stats = cold.stats()
        assert stats["misses"] == 0
        assert stats["artifact_hits"] == 1
        assert stats["entries"] == 1

    def test_cold_build_exports_for_the_next_process(self, tmp_path):
        warm = CompileCache(artifacts=ArtifactStore(str(tmp_path)))
        runner, _args = _aot_runner()
        fn, was_hit = warm.get_or_build(KEY, builder=lambda: runner)
        assert not was_hit
        assert warm.stats()["artifacts"]["saved"] == 1
        # second cache = second process: zero compiles
        cold = CompileCache(artifacts=ArtifactStore(str(tmp_path)))
        _fn, was_hit = cold.get_or_build(
            KEY, builder=lambda: pytest.fail("must not compile")
        )
        assert was_hit
        assert cold.stats()["misses"] == 0

    def test_corrupt_artifact_falls_back_to_builder(self, tmp_path):
        runner, _args = _aot_runner()
        store = ArtifactStore(str(tmp_path))
        path = store.save(KEY, runner)
        corrupt_artifact_file(path)
        built = []
        cache = CompileCache(artifacts=ArtifactStore(str(tmp_path)))
        _fn, was_hit = cache.get_or_build(
            KEY, builder=lambda: built.append(1) or runner
        )
        assert not was_hit and built == [1]
        assert cache.stats()["artifacts"]["rejected_corrupt"] == 1
        # the recompile overwrote the damage
        assert ArtifactStore(str(tmp_path)).load(KEY) is not None
