"""Solver-level unit tests for DynamicMaxSumSolver (VERDICT r2 weak 6:
maxsum_dynamic previously had scenario-level coverage only).

Reference twins: DynamicFactorComputation.change_factor_function
(maxsum_dynamic.py:188) and FactorWithReadOnlyVariableComputation
(:113)."""
import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.maxsum_dynamic import DynamicMaxSumSolver
from pydcop_tpu.dcop import DCOP, Domain, Variable, constraint_from_str
from pydcop_tpu.dcop.objects import ExternalVariable
from pydcop_tpu.ops.compile import compile_factor_graph


def _equality_dcop():
    d = Domain("d", "d", [0, 1])
    dcop = DCOP("dyn", objective="min")
    x, y = Variable("x", d), Variable("y", d)
    dcop.add_constraint(constraint_from_str(
        "c", "0 if x == y else 10", [x, y]))
    # anchor y at 0 so the optimum is unambiguous
    dcop.add_constraint(constraint_from_str("anchor", "y * 1", [y]))
    return dcop


def _solver(dcop, seed=0):
    algo_def = AlgorithmDef.build_with_default_params(
        "maxsum_dynamic", {"noise": 0.0})
    return DynamicMaxSumSolver(
        dcop, compile_factor_graph(dcop), algo_def, seed=seed)


class TestFactorSwap:
    def test_swap_changes_solution(self):
        solver = _solver(_equality_dcop())
        res = solver.run(cycles=20)
        assert res.assignment == {"x": 0, "y": 0}

        dcop = solver.dcop
        scope = list(dcop.constraints["c"].dimensions)
        solver.change_factor_function(constraint_from_str(
            "c", "0 if x != y else 10", scope))
        res = solver.run(cycles=20, resume=True)
        assert res.assignment == {"x": 1, "y": 0}

    def test_swap_lands_in_bucket_slot(self):
        solver = _solver(_equality_dcop())
        dcop = solver.dcop
        scope = list(dcop.constraints["c"].dimensions)
        solver.change_factor_function(constraint_from_str(
            "c", "7 if x == y else 3", scope))
        gi = solver.tensors.factor_names.index("c")
        for b in solver.tensors.buckets:
            where = np.flatnonzero(b.factor_ids == gi)
            if where.size:
                t = np.asarray(b.tensors[int(where[0])])
                slot_names = [
                    solver.tensors.var_names[int(v)]
                    for v in b.var_idx[int(where[0])]
                ]
                # diag = equal values -> 7, off-diag 3 (any axis order)
                assert t[0, 0] == 7 and t[1, 1] == 7
                assert t[0, 1] == 3 and t[1, 0] == 3
                assert set(slot_names) == {"x", "y"}
                return
        raise AssertionError("factor not found in any bucket")

    def test_swap_preserves_message_state(self):
        """A swap is a warm restart: messages are NOT reset (the
        reference's computations keep their state across factor
        changes)."""
        solver = _solver(_equality_dcop())
        solver.run(cycles=10)
        q_before = np.asarray(solver._last_state[0])
        assert np.abs(q_before).sum() > 0  # messages actually developed

        dcop = solver.dcop
        scope = list(dcop.constraints["c"].dimensions)
        solver.change_factor_function(constraint_from_str(
            "c", "0 if x != y else 10", scope))
        # state retained for the resume (run(resume=True) reads it)
        q_after = np.asarray(solver._last_state[0])
        np.testing.assert_array_equal(q_before, q_after)

    def test_swap_rejects_scope_change(self):
        solver = _solver(_equality_dcop())
        d = Domain("d", "d", [0, 1])
        z = Variable("z", d)
        before = solver.dcop.constraints["c"]
        with pytest.raises(ValueError, match="scope"):
            solver.change_factor_function(constraint_from_str(
                "c", "z * 1", [z]))
        # a rejected change must leave the host model untouched — the
        # device tensors were not swapped, so the DCOP must not be either
        assert solver.dcop.constraints["c"] is before

    def test_swap_rejects_unknown_factor(self):
        solver = _solver(_equality_dcop())
        d = Domain("d", "d", [0, 1])
        x = Variable("x", d)
        with pytest.raises(ValueError, match="Unknown factor"):
            solver.change_factor_function(constraint_from_str(
                "nope", "x * 1", [x]))

    def test_swap_respects_scope_order_permutation(self):
        """A replacement constraint may list the same scope in a
        different variable order; the tensor must be transposed into the
        slot's axis order.  (constraint_from_str sorts its scope, so the
        permuted constraint is built directly.)"""
        from pydcop_tpu.dcop.relations import NAryFunctionRelation

        d = Domain("d", "d", [0, 1, 2])
        dcop = DCOP("perm", objective="min")
        a, b = Variable("a", d), Variable("b", d)
        dcop.add_constraint(constraint_from_str("c", "a * 3 + b", [a, b]))
        solver = _solver(dcop)
        # same function, scope listed in REVERSED axis order: axis 0 is
        # b, so f(b, a) = a*3 + b
        solver.change_factor_function(NAryFunctionRelation(
            lambda b_, a_: a_ * 3 + b_, [b, a], "c"))
        new_dims = [v.name for v in
                    solver.dcop.constraints["c"].dimensions]
        assert new_dims == ["b", "a"]  # the transpose branch is real
        gi = solver.tensors.factor_names.index("c")
        for bk in solver.tensors.buckets:
            where = np.flatnonzero(bk.factor_ids == gi)
            if where.size:
                t = np.asarray(bk.tensors[int(where[0])])
                slot_names = [
                    solver.tensors.var_names[int(v)]
                    for v in bk.var_idx[int(where[0])]
                ]
                ia, ib = slot_names.index("a"), slot_names.index("b")
                idx = [0, 0]
                idx[ia], idx[ib] = 2, 1  # a=2, b=1 -> 7
                assert t[tuple(idx)] == 7
                return
        raise AssertionError("factor not found")


class TestExternalVariables:
    def _dcop(self):
        d = Domain("d", "d", [0, 1])
        dcop = DCOP("ext", objective="min")
        x = Variable("x", d)
        sensor = ExternalVariable("sensor", d, value=0)
        dcop.external_variables["sensor"] = sensor
        # x must track the sensor
        dcop.add_constraint(constraint_from_str(
            "track", "0 if x == sensor else 5", [x, sensor]))
        return dcop

    def test_external_change_flips_solution(self):
        dcop = self._dcop()
        solver = _solver(dcop)
        assert solver.run(cycles=15).assignment == {"x": 0}
        solver.on_external_change("sensor", 1)
        assert solver.run(cycles=15, resume=True).assignment == {"x": 1}

    def test_external_slicing_reduces_arity(self):
        """External (read-only) variables are inputs, not decision
        variables: the compiled factor is unary over x."""
        solver = _solver(self._dcop())
        assert solver.tensors.n_vars == 1
        assert all(b.arity == 1 for b in solver.tensors.buckets)
