"""Generator option parity with the reference command surfaces
(VERDICT r2 item 8: reference docs' generate command lines run
unchanged — graphcoloring.py:160-226, meetingscheduling.py:125-192)."""
import subprocess
import sys
import os

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")
ENV = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}


def gen(*args, timeout=60):
    return subprocess.run(
        [sys.executable, "-m", "pydcop_tpu", "generate", *args],
        capture_output=True, text=True, timeout=timeout, env=ENV, cwd=REPO,
    )


class TestGraphColoringOptions:
    def test_intentional_constraints(self):
        from pydcop_tpu.dcop import load_dcop
        from pydcop_tpu.runtime.run import solve

        out = gen("graphcoloring", "-v", "6", "-c", "3", "-g", "random",
                  "-p", "0.5", "--intentional", "--seed", "2")
        assert out.returncode == 0, out.stderr[-500:]
        assert "intention" in out.stdout  # expression form in the YAML
        dcop = load_dcop(out.stdout)
        a = solve(dcop, "dpop")  # hard CSP: optimal has no conflicts
        viol, cost = dcop.solution_cost(a, 10000)
        assert cost < 10000

    def test_intentional_refuses_soft(self):
        out = gen("graphcoloring", "-v", "6", "--soft", "--intentional")
        assert out.returncode != 0

    def test_connected_by_default_subgraphs_on_flag(self):
        from pydcop_tpu.generators import generate_graph_coloring
        from pydcop_tpu.generators.graphcoloring import _is_connected

        # sparse random graph: disconnected when allowed...
        dcop = generate_graph_coloring(
            n_variables=30, n_edges=10, seed=0, allow_subgraph=True)
        # ...the CLI default (allow_subgraph False) filters to connected
        dcop2 = generate_graph_coloring(
            n_variables=12, n_edges=12, seed=0, allow_subgraph=False)
        names = sorted(dcop2.variables)
        pos = {n: i for i, n in enumerate(names)}
        edges = [
            tuple(pos[v.name] for v in c.dimensions)
            for c in dcop2.constraints.values()
        ]
        assert _is_connected(len(names), edges)

    def test_m_edge_controls_scalefree_density(self):
        from pydcop_tpu.generators import generate_graph_coloring

        d2 = generate_graph_coloring(
            n_variables=30, graph_type="scalefree", m_edge=2, seed=1)
        d4 = generate_graph_coloring(
            n_variables=30, graph_type="scalefree", m_edge=4, seed=1)
        assert len(d4.constraints) > len(d2.constraints)

    def test_noagents_and_aliases(self):
        out = gen("graph_coloring", "-v", "9", "-c", "3", "-g", "grid",
                  "--noagents")
        assert out.returncode == 0, out.stderr[-500:]
        assert "agents: {}" in out.stdout


class TestMeetingsPeav:
    def test_reference_docs_command_line(self, tmp_path):
        """The exact example from the reference docs (module docstring
        meetingscheduling.py:96-104) runs unchanged and emits both the
        DCOP and its PEAV distribution."""
        out_file = tmp_path / "meetings.yaml"
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu",
             "--output", str(out_file), "generate", "meetings",
             "--slots_count", "5", "--events_count", "6",
             "--resources_count", "3", "--max_resources_event", "2",
             "--max_length_event", "2"],
            capture_output=True, text=True, timeout=60, env=ENV, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        dist_file = tmp_path / "meetings_dist.yaml"
        assert out_file.exists() and dist_file.exists()

        import yaml

        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(str(out_file))
        assert dcop.objective == "max"
        # one agent per resource, hosting its own event-copy variables
        dist = yaml.safe_load(dist_file.read_text())
        assert dist["inputs"]["dist_algo"] == "peav"
        hosted = [v for vs in dist["distribution"].values() for v in vs]
        assert sorted(hosted) == sorted(dcop.variables)

    def test_peav_solves(self):
        from pydcop_tpu.generators import generate_meetings_peav
        from pydcop_tpu.runtime.run import solve

        dcop, mapping = generate_meetings_peav(
            slots_count=4, events_count=3, resources_count=3,
            max_resources_event=2, seed=3,
        )
        assert mapping is not None
        a = solve(dcop, "dpop")
        # every scheduled copy of an event agrees on its start slot
        starts = {}
        for name, val in a.items():
            e = name.rsplit("_", 1)[-1]
            starts.setdefault(e, set()).add(val)
        assert all(len(s) == 1 for s in starts.values())

    def test_no_agents(self):
        from pydcop_tpu.generators import generate_meetings_peav

        dcop, mapping = generate_meetings_peav(
            slots_count=4, events_count=2, resources_count=2,
            max_resources_event=2, seed=1, no_agents=True,
        )
        assert mapping is None and not dcop.agents


class TestIotOptions:
    def test_reference_flags_and_dist_output(self, tmp_path):
        out_file = tmp_path / "iot.yaml"
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu",
             "--output", str(out_file), "generate", "iot",
             "-d", "4", "-n", "8", "-r", "10"],
            capture_output=True, text=True, timeout=120, env=ENV, cwd=REPO,
        )
        assert proc.returncode == 0, proc.stderr[-500:]
        assert out_file.exists()
        assert (tmp_path / "iot_dist.yaml").exists()

        from pydcop_tpu.dcop import load_dcop_from_file

        dcop = load_dcop_from_file(str(out_file))
        assert len(dcop.variables) == 8
        assert all(len(v.domain) == 4 for v in dcop.variables.values())
