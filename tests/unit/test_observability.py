"""Tests for the UI server, stats tracing, and metric collection."""
import json
import os
import urllib.request

import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime.events import event_bus
from pydcop_tpu.runtime.stats import StatsLogger, cycle_op_counts
from pydcop_tpu.runtime.ui import UiServer

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def tuto():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestUiServer:
    def test_state_endpoint(self):
        port = _free_port()
        ui = UiServer(port=port, ws_port=_free_port())
        ui.start()
        try:
            ui.update_state(status="RUNNING", cycle=3)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/state", timeout=5
            ) as resp:
                state = json.loads(resp.read())
            assert state["status"] == "RUNNING"
            assert state["cycle"] == 3
        finally:
            ui.stop()
            event_bus.unsubscribe(ui._on_event)

    def test_unknown_endpoint_404(self):
        port = _free_port()
        ui = UiServer(port=port, ws_port=_free_port())
        ui.start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=5
                )
        finally:
            ui.stop()
            event_bus.unsubscribe(ui._on_event)


class TestStats:
    def test_op_counts(self, tuto):
        from pydcop_tpu.ops import compile_factor_graph

        tensors = compile_factor_graph(tuto)
        ops, nc_ops = cycle_op_counts(tensors)
        # 4 binary factors with D=2: 4 * 2*2 * 2 positions = 32 table reads
        assert ops == 32
        assert nc_ops == 8  # one factor's worth (critical path)

    def test_trace_and_dump(self, tuto, tmp_path):
        from pydcop_tpu.ops import compile_factor_graph

        tensors = compile_factor_graph(tuto)
        logger = StatsLogger()
        for c in range(3):
            logger.trace_cycle("maxsum", c, tensors, cost=10.0 - c,
                              msg_count=16)
        path = str(tmp_path / "stats.csv")
        logger.dump(path)
        lines = open(path).read().strip().splitlines()
        assert len(lines) == 4
        assert lines[0].startswith("timestamp,computation,cycle,op_count")


class TestRunLocalApi:
    def test_run_local_thread_dcop_with_collector(self, tuto):
        """Reference-parity integration path: build orchestrator via
        run_local_thread_dcop, collect run metrics, read end metrics."""
        from pydcop_tpu.runtime import run_local_thread_dcop

        collected = []
        orch = run_local_thread_dcop(
            tuto, "maxsum", distribution="adhoc",
            collector=lambda t, m: collected.append((t, m)),
            collect_moment="cycle_change",
        )
        res = orch.run(timeout=20)
        assert res.cost == 12
        assert collected, "collector must receive per-cycle metrics"
        t, m = collected[-1]
        assert "cost" in m and "cycle" in m
