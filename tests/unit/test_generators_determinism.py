"""Seed audit of the generator families (ISSUE 10 satellite): every
family accepts an explicit seed and produces BYTE-IDENTICAL YAML for
the same seed — the portfolio dataset harness keys its resumable
sweep cells on (family, size, seed), so a family leaking global RNG
state would silently relabel cells across resumes.

All randomness must flow from ``random.Random(seed)`` /
``np.random.default_rng(seed)`` locals; the global ``random`` module
is perturbed before each generation to catch any fallback to it.
"""
import random

import numpy as np
import pytest

from pydcop_tpu.dcop.yamldcop import dcop_yaml, yaml_agents
from pydcop_tpu.generators import (
    generate_agents,
    generate_graph_coloring,
    generate_iot,
    generate_ising,
    generate_meeting_scheduling,
    generate_meetings_peav,
    generate_routing,
    generate_routing_structured,
    generate_scenario,
    generate_secp,
    generate_smallworld,
    generate_tracking,
    tracking_scenario,
)

FAMILIES = {
    "graphcoloring": lambda seed: generate_graph_coloring(
        n_variables=10, n_colors=3, n_edges=18, soft=True, seed=seed),
    "graphcoloring_scalefree": lambda seed: generate_graph_coloring(
        n_variables=10, graph_type="scalefree", m_edge=2, soft=True,
        seed=seed),
    "ising": lambda seed: generate_ising(rows=4, seed=seed)[0],
    "smallworld": lambda seed: generate_smallworld(
        n_variables=12, seed=seed),
    "iot": lambda seed: generate_iot(n_devices=8, seed=seed),
    "secp": lambda seed: generate_secp(n_lights=5, seed=seed),
    "meetingscheduling": lambda seed: generate_meeting_scheduling(
        n_agents=4, n_meetings=3, seed=seed),
    "meetings_peav": lambda seed: generate_meetings_peav(
        slots_count=4, events_count=3, resources_count=3,
        max_resources_event=2, seed=seed)[0],
    "routing": lambda seed: generate_routing(10, n_slots=4, seed=seed),
    "routing_infeasible": lambda seed: generate_routing(
        8, n_slots=4, infeasible=True, seed=seed),
    "routing_structured": lambda seed: generate_routing_structured(
        10, n_slots=4, p_soft=0.3, seed=seed),
    "routing_structured_wide": lambda seed: generate_routing_structured(
        24, n_slots=4, window=12, seed=seed),
    "tracking": lambda seed: generate_tracking(
        16, n_targets=2, seed=seed),
}


def _yaml(family, seed):
    # poison the GLOBAL RNG streams differently before each build: a
    # generator falling back to them would diverge between the calls
    random.seed(seed * 7919 + len(family))
    np.random.seed((seed * 104729 + 1) % 2**31)
    return dcop_yaml(FAMILIES[family](seed))


class TestGeneratorDeterminism:
    @pytest.mark.parametrize("family", sorted(FAMILIES))
    def test_same_seed_byte_identical_yaml(self, family):
        assert _yaml(family, 3) == _yaml(family, 3)

    @pytest.mark.parametrize("family", sorted(
        set(FAMILIES) - {"iot"}  # iot's topology is seed-random too,
    ))                           # asserted below with its own params
    def test_different_seed_differs(self, family):
        assert _yaml(family, 1) != _yaml(family, 2)

    def test_iot_different_seed_differs(self):
        assert _yaml("iot", 1) != _yaml("iot", 4)

    def test_agents_generator_deterministic(self):
        def build(seed):
            random.seed(seed + 17)
            return yaml_agents(generate_agents(
                6, route_range=(1, 9), seed=seed))

        assert build(5) == build(5)
        assert build(5) != build(6)


class TestStructuredRoundTrip:
    """Table-free satellite: ``type: structured`` YAML round-trips by
    parameters — loading must NOT silently densify (the old behavior),
    and dump(load(dump(d))) is byte-canonical."""

    def test_yaml_round_trip_preserves_structure(self):
        from pydcop_tpu.dcop.structured import StructuredConstraint
        from pydcop_tpu.dcop.yamldcop import load_dcop

        d = generate_routing_structured(10, n_slots=4, p_soft=0.3, seed=2)
        y1 = dcop_yaml(d)
        d2 = load_dcop(y1)
        assert dcop_yaml(d2) == y1
        orig = {c.name for c in d.constraints.values()
                if isinstance(c, StructuredConstraint)}
        back = {c.name for c in d2.constraints.values()
                if isinstance(c, StructuredConstraint)}
        assert orig and back == orig

    def test_wide_window_dumps_without_densifying(self):
        from pydcop_tpu.dcop.structured import StructuredConstraint
        from pydcop_tpu.dcop.yamldcop import load_dcop

        # the 100-arity window's dense twin would hold 4**100 entries;
        # dumping succeeds only through the parameter form
        d = generate_routing_structured(100, n_slots=4, window=100,
                                        p_soft=0.0, seed=0)
        d2 = load_dcop(dcop_yaml(d))
        assert any(
            isinstance(c, StructuredConstraint) and c.arity == 100
            for c in d2.constraints.values()
        )


def _scenario_canon(scenario):
    """Canonical byte string of a scenario's event stream — order,
    ids, delays and every action's full parameter set."""
    return repr([
        (e.id, e.delay,
         [(a.type, sorted(a.parameters.items())) for a in e.actions])
        for e in scenario
    ])


class TestScenarioDeterminism:
    """ISSUE 12 satellite: the twin replays its churn streams from
    their seeds, so every SCENARIO builder must be byte-deterministic
    under global-RNG poisoning too — a stream that drifted between a
    run and its replay would silently change which constraints mutate.
    """

    def _poison(self, seed):
        random.seed(seed * 31 + 5)
        np.random.seed((seed * 7919 + 3) % 2**31)

    def test_generate_scenario_deterministic(self):
        def build(seed):
            self._poison(seed)
            return _scenario_canon(generate_scenario(
                [f"a{i}" for i in range(8)], n_events=4,
                removals_per_event=2, seed=seed))

        assert build(3) == build(3)
        assert build(3) != build(4)

    def test_churn_scenario_deterministic(self):
        from pydcop_tpu.dcop.scenario import churn_scenario

        def build(seed):
            self._poison(seed)
            dcop = generate_graph_coloring(
                n_variables=10, n_colors=3, n_edges=18, soft=True,
                seed=1)
            return _scenario_canon(churn_scenario(
                dcop, n_events=6, seed=seed))

        assert build(3) == build(3)
        assert build(3) != build(4)

    def test_tracking_scenario_deterministic(self):
        def build(seed):
            self._poison(seed)
            dcop = generate_tracking(16, n_targets=2, seed=seed)
            return _scenario_canon(tracking_scenario(dcop, 3))

        assert build(3) == build(3)
        assert build(3) != build(4)


class TestCanonicalFormDeterminism:
    """ISSUE 18 satellite: the solution cache keys entries on the
    canonical byte form (pydcop_tpu.dcop.canonical), so EVERY
    generator family must canonicalize byte-identically under
    global-RNG poisoning — a hash that drifted between two identical
    submissions would turn exact duplicates into cache misses (safe
    but useless), and a collision would serve the wrong solution."""

    def _canon(self, family, seed):
        from pydcop_tpu.dcop.canonical import canonical_bytes

        random.seed(seed * 131 + len(family))
        np.random.seed((seed * 31337 + 11) % 2**31)
        return canonical_bytes(FAMILIES[family](seed))

    @pytest.mark.parametrize("family", sorted(FAMILIES))
    def test_same_seed_byte_identical_canonical_form(self, family):
        assert self._canon(family, 3) == self._canon(family, 3)

    @pytest.mark.parametrize("family", sorted(
        set(FAMILIES) - {"iot"}  # iot topology randomness pinned above
    ))
    def test_different_seed_canonical_hash_differs(self, family):
        from pydcop_tpu.dcop.canonical import canonical_hash

        random.seed(1)
        np.random.seed(1)
        h1 = canonical_hash(FAMILIES[family](1))
        random.seed(1)
        np.random.seed(1)
        h2 = canonical_hash(FAMILIES[family](2))
        assert h1 != h2

    def test_no_cross_family_collisions(self):
        from pydcop_tpu.dcop.canonical import canonical_hash

        hashes = [canonical_hash(FAMILIES[f](3)) for f in sorted(FAMILIES)]
        assert len(set(hashes)) == len(hashes)
