"""Cross-request solution cache (ISSUE 18 tentpole).

Contracts pinned here:

* **canonicalization** — byte-identical canonical form under
  global-RNG poisoning; variable/factor declaration-order permutations
  hash identically; the instance name/description never leaks into the
  hash; semantically different instances (different seed, different
  table, different scope order) never collide;
* **hit taxonomy** — exact hits replay the cached result
  BIT-IDENTICALLY (assignment, cost, cycle) with zero device work;
  variant hits warm-start from the embedding-matched nearest cached
  solution and replay only the factor diff; everything else is a miss;
* **never-worse guarantee** — per warm-capable algo: a served
  warm-start result costs no more than the cold solve of the same
  variant on the same seed, and the gate falls back to cold (returns
  ``None``) rather than serve a regression;
* **invalidation** — TTL expiry, tenant-scoped churn events, LRU
  eviction, per-tenant namespace isolation;
* **persistence** — entries rehydrate from CRC'd npz beside the
  journal; a corrupt entry (the ``corrupt_cache_entry`` fault) is
  skipped-and-counted, NEVER served — both via direct byte-flips and
  via the seeded fault plan through a live service;
* **service integration** — the tick-driven SolveService probes the
  cache at admission, serves hits without occupying a lane, stamps
  ``metrics()["memo"]`` provenance on every job, and ``resume()``
  rehydrates the cache.
"""
import os
import random

import numpy as np
import pytest

from pydcop_tpu.dcop.canonical import (
    canonical_bytes,
    canonical_hash,
    constraint_digests,
    factor_diff,
    shape_signature,
)
from pydcop_tpu.dcop.yamldcop import dcop_yaml, load_dcop
from pydcop_tpu.generators import generate_graph_coloring
from pydcop_tpu.runtime.repair import perturbed_constraint
from pydcop_tpu.serve.memo import MemoCache, MemoConfig


def _instance(seed=3, n=10):
    return generate_graph_coloring(
        n_variables=n, n_colors=3, n_edges=2 * n - 2, soft=True,
        seed=seed)


def _poison(salt):
    """Perturb the global RNG streams: canonicalization consulting
    them would diverge between two calls."""
    random.seed(salt * 7919 + 13)
    np.random.seed((salt * 104729 + 7) % 2**31)


def _variant(seed=3, n=10, edit_seed=9, which=2):
    """The base instance with ONE constraint's table jittered."""
    d = _instance(seed, n)
    name = sorted(d.constraints)[which]
    d.constraints[name] = perturbed_constraint(
        d.constraints[name], seed=edit_seed)
    return d


def _cold(dcop, algo, seed=1, cycles=300):
    from pydcop_tpu.runtime.run import solve_result

    return solve_result(dcop, algo, seed=seed, cycles=cycles)


# ---------------------------------------------------------------------------
# canonicalization
# ---------------------------------------------------------------------------


class TestCanonicalDeterminism:
    def test_byte_identical_under_rng_poisoning(self):
        _poison(1)
        b1 = canonical_bytes(_instance())
        _poison(2)
        b2 = canonical_bytes(_instance())
        assert b1 == b2

    def test_declaration_order_permutation_hashes_identically(self):
        d = _instance()
        y = dcop_yaml(d)
        d2 = load_dcop(y)
        # permute the declaration order of every name-keyed section:
        # content addressing must not see it
        for attr in ("_variables", "_constraints", "_agents"):
            section = getattr(d2, attr, None)
            if not isinstance(section, dict) or len(section) < 2:
                continue
            items = list(section.items())
            random.Random(5).shuffle(items)
            section.clear()
            section.update(items)
        assert canonical_hash(d2) == canonical_hash(d)
        assert shape_signature(d2) == shape_signature(d)

    def test_yaml_round_trip_hash_stable(self):
        d = _instance()
        assert canonical_hash(load_dcop(dcop_yaml(d))) \
            == canonical_hash(d)

    def test_name_metadata_excluded(self):
        d1, d2 = _instance(), _instance()
        d2.name = "a-completely-different-label"
        assert canonical_hash(d1) == canonical_hash(d2)

    def test_different_instances_never_collide(self):
        seen = {canonical_hash(_instance(seed=s)) for s in range(6)}
        assert len(seen) == 6

    def test_single_table_edit_changes_hash_not_shape(self):
        d, v = _instance(), _variant()
        assert canonical_hash(d) != canonical_hash(v)
        assert shape_signature(d) == shape_signature(v)

    def test_factor_diff_localizes_the_edit(self):
        d, v = _instance(), _variant(which=2)
        diff = factor_diff(constraint_digests(d), v)
        assert diff.edits == 1
        assert diff.changed == [sorted(d.constraints)[2]]
        assert not diff.added and not diff.removed

    def test_factor_diff_added_removed(self):
        d, v = _instance(), _instance()
        name = sorted(v.constraints)[0]
        c = v.constraints.pop(name)
        diff = factor_diff(constraint_digests(d), v)
        assert diff.removed == [name] and diff.edits == 1
        v.constraints[name] = c
        diff2 = factor_diff(constraint_digests(v), d)
        assert diff2.edits == 0


# ---------------------------------------------------------------------------
# cache core: hit taxonomy
# ---------------------------------------------------------------------------


class TestHitTaxonomy:
    def test_miss_then_exact_hit_bit_identical(self):
        cache = MemoCache()
        d = _instance()
        p1 = cache.probe(d, "mgm", seed=1)
        assert p1.kind == "miss"
        cold = _cold(d, "mgm")
        entry = cache.memoize(p1, d, cold)
        assert entry is not None
        p2 = cache.probe(d, "mgm", seed=1)
        assert p2.kind == "exact"
        res = cache.result_from_entry(p2.entry, p2)
        assert res.assignment == cold.assignment
        assert res.cost == cold.cost and res.cycle == cold.cycle
        assert res.memo["hit"] == "exact"

    def test_seed_algo_params_tenant_are_namespaces(self):
        cache = MemoCache()
        d = _instance()
        p = cache.probe(d, "mgm", seed=1, tenant="t1")
        cache.memoize(p, d, _cold(d, "mgm"))
        assert cache.probe(d, "mgm", seed=2, tenant="t1").kind != "exact"
        assert cache.probe(d, "dsa", seed=1, tenant="t1").kind != "exact"
        assert cache.probe(d, "mgm", seed=1, tenant="t2").kind != "exact"
        assert cache.probe(d, "mgm", seed=1, tenant="t1",
                           algo_params={"x": 1}).kind != "exact"
        assert cache.probe(d, "mgm", seed=1, tenant="t1").kind == "exact"

    def test_variant_hit_replays_factor_diff_warm(self):
        cache = MemoCache()
        d, v = _instance(), _variant()
        p = cache.probe(d, "mgm", seed=1)
        cold = _cold(d, "mgm")
        cache.memoize(p, d, cold)
        pv = cache.probe(v, "mgm", seed=1)
        assert pv.kind == "variant"
        assert pv.diff.edits == 1
        res = cache.serve_variant(pv, v)
        assert res is not None
        assert res.memo["hit"] == "variant"
        assert res.memo["edits"] == 1
        # served result satisfies the never-worse gate vs the seed
        viol, c_seed = v.solution_cost(dict(p.entry.assignment
                                            if p.entry else
                                            cold.assignment), 1e9)
        assert res.cost <= c_seed + 1e-6

    def test_variant_gate_rejects_large_diffs(self):
        cache = MemoCache(MemoConfig(max_edits=1))
        d = _instance()
        p = cache.probe(d, "mgm", seed=1)
        cache.memoize(p, d, _cold(d, "mgm"))
        v = _instance()
        for which in (1, 2, 3):
            name = sorted(v.constraints)[which]
            v.constraints[name] = perturbed_constraint(
                v.constraints[name], seed=11 + which)
        pv = cache.probe(v, "mgm", seed=1)
        assert pv.kind == "miss"
        assert cache.counters.counts["variant_rejected_gate"] >= 1

    def test_non_warm_algo_never_matches_variants(self):
        cache = MemoCache()
        d = _instance()
        p = cache.probe(d, "gdba", seed=1)
        cold = _cold(d, "gdba")
        cache.memoize(p, d, cold)
        # exact still works for any algo...
        assert cache.probe(d, "gdba", seed=1).kind == "exact"
        # ...but a variant of a non-warm algo is a plain miss
        assert cache.probe(_variant(), "gdba", seed=1).kind == "miss"


# ---------------------------------------------------------------------------
# never-worse guarantee, per warm-capable algo
# ---------------------------------------------------------------------------


class TestNeverWorse:
    @pytest.mark.parametrize("algo", ["mgm", "dsa", "adsa", "maxsum"])
    def test_warm_cost_never_worse_than_cold_same_seed(self, algo):
        # Local search is monotone from the seeded assignment, so the
        # strict warm-vs-cold comparison is stable even at n=10.  maxsum
        # is message passing: the warm engine's headroom-padded slabs
        # reach a slightly different fixed point than the cold dense
        # engine, and at n=10 which one wins is hash-order noise (cold
        # maxsum itself returned 48.36 / 8.93 / 29.47 across three
        # processes on the same instance; PYTHONHASHSEED=0 pins it).  At
        # n=60 — the size the bench leg pins — warm matches or beats
        # cold across hash seeds, or the gate refuses and the job falls
        # back cold, which holds the guarantee by refusal.
        n = 60 if algo == "maxsum" else 10
        cache = MemoCache()
        d, v = _instance(n=n), _variant(n=n)
        p = cache.probe(d, algo, seed=1)
        cache.memoize(p, d, _cold(d, algo))
        pv = cache.probe(v, algo, seed=1)
        assert pv.kind == "variant"
        res = cache.serve_variant(pv, v)
        cold_v = _cold(v, algo)
        if res is None:
            # gate refused to serve: the job falls back to cold — the
            # guarantee holds trivially
            assert cache.counters.counts["variant_cold_fallbacks"] >= 1
        else:
            assert res.cost <= cold_v.cost + 1e-6

    def test_gate_falls_back_instead_of_serving_regression(self):
        # a hostile cycle budget (0 cycles of repair after mutation
        # replay) cannot make the gate serve a worse-than-seed result:
        # either the seeded cost stands, or None comes back
        cache = MemoCache(MemoConfig(warm_max_cycles=1))
        d, v = _instance(), _variant()
        p = cache.probe(d, "mgm", seed=1)
        cache.memoize(p, d, _cold(d, "mgm"))
        pv = cache.probe(v, "mgm", seed=1)
        res = cache.serve_variant(pv, v)
        if res is not None:
            _viol, c_seed = v.solution_cost(
                dict(pv.entry.assignment), 1e9)
            assert res.cost <= c_seed + 1e-6


# ---------------------------------------------------------------------------
# invalidation: TTL / churn / LRU / namespaces
# ---------------------------------------------------------------------------


class TestInvalidation:
    def test_ttl_expiry_drops_entries(self):
        cache = MemoCache(MemoConfig(ttl_s=0.01))
        d = _instance()
        p = cache.probe(d, "mgm", seed=1)
        cache.memoize(p, d, _cold(d, "mgm"))
        import time

        time.sleep(0.05)
        assert cache.probe(d, "mgm", seed=1).kind == "miss"
        assert cache.counters.counts["expired_ttl"] == 1
        assert len(cache) == 0

    def test_churn_event_is_tenant_scoped(self):
        cache = MemoCache()
        d = _instance()
        cold = _cold(d, "mgm")
        for tenant in ("t1", "t2"):
            p = cache.probe(d, "mgm", seed=1, tenant=tenant)
            cache.memoize(p, d, cold)
        assert cache.churn_event("t1") == 1
        assert cache.probe(d, "mgm", seed=1, tenant="t1").kind == "miss"
        assert cache.probe(d, "mgm", seed=1, tenant="t2").kind == "exact"
        assert cache.churn_event() == 1  # drop everything left

    def test_lru_eviction_bounds_the_cache(self):
        cache = MemoCache(MemoConfig(max_entries=2))
        cold = _cold(_instance(), "mgm")
        for s in range(4):
            d = _instance(seed=s)
            p = cache.probe(d, "mgm", seed=1)
            cache.memoize(p, d, cold)
        assert len(cache) == 2
        assert cache.counters.counts["evicted_lru"] == 2


# ---------------------------------------------------------------------------
# persistence: rehydrate / corruption / adoption
# ---------------------------------------------------------------------------


class TestPersistence:
    def _populated(self, tmp_path):
        cache = MemoCache(directory=str(tmp_path / "memo"))
        d = _instance()
        p = cache.probe(d, "mgm", seed=1)
        cold = _cold(d, "mgm")
        entry = cache.memoize(p, d, cold)
        return cache, d, cold, entry

    def test_rehydrate_restores_exact_hits(self, tmp_path):
        cache, d, cold, entry = self._populated(tmp_path)
        assert entry.path and os.path.exists(entry.path)
        fresh = MemoCache(directory=cache.directory)
        assert fresh.rehydrate() == 1
        p = fresh.probe(d, "mgm", seed=1)
        assert p.kind == "exact"
        res = fresh.result_from_entry(p.entry, p)
        assert res.assignment == cold.assignment
        assert res.cost == cold.cost

    def test_corrupt_entry_skipped_and_counted_never_served(
            self, tmp_path):
        cache, d, _cold_res, entry = self._populated(tmp_path)
        assert cache.corrupt_entry(entry.key) == entry.path
        fresh = MemoCache(directory=cache.directory)
        assert fresh.rehydrate() == 0
        assert fresh.counters.counts["corrupt_skipped"] == 1
        assert fresh.probe(d, "mgm", seed=1).kind == "miss"

    def test_adopt_file_peer_sharing(self, tmp_path):
        cache, d, cold, entry = self._populated(tmp_path)
        peer = MemoCache()
        assert peer.adopt_file(entry.path)
        p = peer.probe(d, "mgm", seed=1)
        assert p.kind == "exact"
        assert peer.result_from_entry(p.entry, p).cost == cold.cost
        # adopted entries are NOT owned: evicting them on the peer
        # must not unlink the owner's file
        peer.churn_event()
        assert os.path.exists(entry.path)

    def test_adopt_file_refuses_corrupt_peer_entry(self, tmp_path):
        cache, d, _cold_res, entry = self._populated(tmp_path)
        cache.corrupt_entry(entry.key)
        peer = MemoCache()
        assert not peer.adopt_file(entry.path)
        assert peer.counters.counts["corrupt_skipped"] == 1
        assert len(peer) == 0

    def test_adopt_entry_dedupes_by_key(self, tmp_path):
        cache, d, _cold_res, entry = self._populated(tmp_path)
        peer = MemoCache()
        assert peer.adopt_entry(entry)
        assert not peer.adopt_entry(entry)
        assert peer.counters.counts["adopted"] == 1


# ---------------------------------------------------------------------------
# service integration (tick-driven — no scheduler thread)
# ---------------------------------------------------------------------------


def _drain(svc, max_ticks=200):
    for _ in range(max_ticks):
        if not svc.tick():
            return


class TestServiceIntegration:
    def _svc(self, tmp_path=None, **kw):
        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.serve import SolveService

        jd = str(tmp_path / "journal") if tmp_path is not None else None
        return SolveService(lanes=4, cache=CompileCache(),
                            journal_dir=jd, memo=True, **kw)

    def test_exact_hit_serves_without_solving(self, tmp_path):
        svc = self._svc()
        d = _instance()
        j1 = svc.submit(d, "mgm", seed=1)
        _drain(svc)
        r1 = svc.result(j1, timeout=1)
        assert r1.metrics()["memo"]["hit"] == "miss"
        j2 = svc.submit(d, "mgm", seed=1)
        _drain(svc)
        r2 = svc.result(j2, timeout=1)
        m = r2.metrics()["memo"]
        assert m["hit"] == "exact"
        assert r2.assignment == r1.assignment and r2.cost == r1.cost
        assert svc.metrics()["memo"]["hits_exact"] == 1

    def test_variant_hit_provenance_and_guarantee(self):
        svc = self._svc()
        d, v = _instance(), _variant()
        j1 = svc.submit(d, "mgm", seed=1)
        _drain(svc)
        r1 = svc.result(j1, timeout=1)
        j2 = svc.submit(v, "mgm", seed=1)
        _drain(svc)
        r2 = svc.result(j2, timeout=1)
        m = r2.metrics()["memo"]
        assert m["hit"] in ("variant", "miss")
        if m["hit"] == "variant":
            assert m["edits"] == 1
            _viol, c_seed = v.solution_cost(dict(r1.assignment), 1e9)
            assert r2.cost <= c_seed + 1e-6
        else:  # warm gate fell back: solved cold, flagged as such
            assert m.get("cold_fallback")

    def test_resume_rehydrates_cache(self, tmp_path):
        svc = self._svc(tmp_path)
        d = _instance()
        yaml_path = tmp_path / "inst.yaml"
        yaml_path.write_text(dcop_yaml(d))
        j1 = svc.submit(d, "mgm", seed=1, source_file=str(yaml_path))
        _drain(svc)
        r1 = svc.result(j1, timeout=1)
        del svc  # crash

        svc2 = self._svc(tmp_path)
        svc2.resume()
        assert svc2.metrics()["memo"]["rehydrated"] == 1
        j2 = svc2.submit(d, "mgm", seed=1)
        _drain(svc2)
        r2 = svc2.result(j2, timeout=1)
        assert r2.metrics()["memo"]["hit"] == "exact"
        assert r2.assignment == r1.assignment and r2.cost == r1.cost

    def test_corrupt_cache_entry_fault_plan(self, tmp_path):
        from pydcop_tpu.runtime.faults import Fault, FaultPlan

        plan = FaultPlan(
            faults=[Fault(kind="corrupt_cache_entry",
                          jid="job-000001")],
            seed=7,
        )
        svc = self._svc(tmp_path, fault_plan=plan)
        d = _instance()
        j1 = svc.submit(d, "mgm", seed=1)
        _drain(svc)
        svc.result(j1, timeout=1)
        assert svc.counters.counts["faults_injected"] >= 1
        # the in-memory entry still hits, but the PERSISTED npz is
        # corrupt: a restarted service must skip-and-count it
        del svc
        svc2 = self._svc(tmp_path)
        svc2.resume()
        m = svc2.metrics()["memo"]
        assert m["corrupt_skipped"] == 1 and m["rehydrated"] == 0
        j2 = svc2.submit(d, "mgm", seed=1)
        _drain(svc2)
        r2 = svc2.result(j2, timeout=1)
        assert r2.metrics()["memo"]["hit"] == "miss"

    def test_churn_event_invalidates_served_results(self):
        svc = self._svc()
        d = _instance()
        j1 = svc.submit(d, "mgm", seed=1, tenant="t1")
        _drain(svc)
        svc.result(j1, timeout=1)
        assert svc.churn_event("t1") == 1
        j2 = svc.submit(d, "mgm", seed=1, tenant="t1")
        _drain(svc)
        r2 = svc.result(j2, timeout=1)
        assert r2.metrics()["memo"]["hit"] == "miss"
        assert svc.metrics()["memo"]["invalidated_churn"] == 1


# ---------------------------------------------------------------------------
# fleet sharing (thread fleet, tick-driven)
# ---------------------------------------------------------------------------


class TestFleetSharing:
    def test_insert_adopted_by_peers(self, tmp_path):
        from pydcop_tpu.serve import SolveFleet

        fl = SolveFleet(replicas=2, lanes=4,
                        journal_dir=str(tmp_path / "fleet"),
                        memo=True)
        d = _instance()
        j1 = fl.submit(d, "mgm", seed=1)
        for _ in range(300):
            fl.tick()
            try:
                fl.result(j1, timeout=0.01)
                break
            except TimeoutError:
                continue
        r1 = fl.result(j1, timeout=1)
        met = fl.metrics()
        adopted = sum((rep["memo"] or {}).get("adopted", 0)
                      for rep in met["replicas"].values())
        assert adopted == 1
        assert met["fleet"]["memo_shared"] == 1
        # the journal carries the share record
        import json

        recs = [json.loads(line.split(" ", 1)[-1])
                if not line.startswith("{") else json.loads(line)
                for line in open(
                    os.path.join(str(tmp_path / "fleet"),
                                 "fleet.jsonl"))
                if line.strip().startswith("{")]
        assert any(r.get("kind") == "memo" for r in recs)
        # a duplicate is an exact hit on EVERY replica
        for _ in range(2):
            j = fl.submit(d, "mgm", seed=1)
            for _ in range(300):
                fl.tick()
                try:
                    fl.result(j, timeout=0.01)
                    break
                except TimeoutError:
                    continue
            r = fl.result(j, timeout=1)
            assert r.metrics()["memo"]["hit"] == "exact"
            assert r.assignment == r1.assignment
            assert r.cost == r1.cost
        fl.stop(drain=False)
