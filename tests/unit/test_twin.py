"""City-scale digital twin (ISSUE 12): the new workload families, the
SLO guardrail ladder, the serve-layer levers it pulls, and the twin
runner end to end.

Acceptance pins carried here:

* routing instances provably exercise CEC pruning — nonzero pruned
  wire bytes in ``metrics()["dpop"]`` — and the infeasible variant is
  genuinely infeasible (violation >= 1 under an exact solve);
* tracking instances drive warm repair with ZERO retraces;
* both families solve end-to-end through ``solve`` AND serve
  admission;
* the ladder escalates deterministically, releases with hysteresis,
  and its three rungs pull real levers (shed / deadline pressure /
  emptiest placement);
* a twin run under the combined chaos plan keeps FINISHED jobs
  bit-identical to standalone solves.
"""
import queue

import numpy as np
import pytest

from pydcop_tpu.generators import (
    generate_routing,
    generate_tracking,
    tracking_scenario,
)
from pydcop_tpu.generators.routing import HARD_COST, is_infeasible_cost
from pydcop_tpu.generators.tracking import (
    moved_constraint,
    step_mutations,
    target_positions,
)
from pydcop_tpu.runtime.events import event_bus
from pydcop_tpu.runtime.faults import Fault, FaultPlan
from pydcop_tpu.runtime.run import solve_result
from pydcop_tpu.runtime.stats import SloCounters
from pydcop_tpu.scenario import (
    JobScore,
    SloLadder,
    TierSpec,
    TwinRunner,
    build_twin_traffic,
    default_chaos_plan,
    default_tiers,
    standalone_results,
)


# ---------------------------------------------------------------------------
# routing: hard-constraint density, infeasibility, CEC pruning
# ---------------------------------------------------------------------------


class TestRoutingFamily:
    def test_hard_cost_matches_exact_engine_sentinel(self):
        from pydcop_tpu.ops.dpop_sweep import BIG

        assert HARD_COST == BIG

    def test_feasible_instance_solves_clean(self):
        dcop = generate_routing(10, n_slots=4, seed=3)
        res = solve_result(dcop, "dpop")
        assert res.status == "FINISHED"
        assert res.violation == 0
        assert res.cost < HARD_COST / 4
        assert not is_infeasible_cost(
            dcop.solution_cost(res.assignment, 1e12)[1]
        )

    def test_mgm_end_to_end(self):
        dcop = generate_routing(12, n_slots=4, seed=5)
        res = solve_result(dcop, "mgm", cycles=80)
        assert res.status == "FINISHED"
        assert res.violation == 0

    def test_infeasible_variant_is_genuinely_infeasible(self):
        """k tasks on k-1 equal slots: by pigeonhole NO assignment
        avoids a hard violation — the exact optimum carries >= 1
        violation and a raw cost >= BIG."""
        bad = generate_routing(10, n_slots=4, infeasible=True, seed=3)
        res = solve_result(bad, "dpop")
        assert res.violation >= 1
        raw = bad.solution_cost(res.assignment, 1e12)[1]
        assert is_infeasible_cost(raw)

    def test_rejects_silent_pigeonhole(self):
        with pytest.raises(ValueError, match="infeasible"):
            generate_routing(10, n_slots=3, tasks_per_resource=4)

    def test_cec_pruning_fires_on_the_wire(self):
        """The acceptance pin: a routing instance solved through the
        separator-sharded sweep ships a PRUNED wire — nonzero pruned
        bytes, strictly below dense — and stays bit-identical to the
        single-device sweep (pruning is sound)."""
        dcop = generate_routing(10, n_slots=4, seed=3)
        ref = solve_result(dcop, "dpop")
        res = solve_result(
            dcop, "dpop", algo_params={"engine": "sharded", "shards": 2},
        )
        m = res.metrics()["dpop"]
        assert m["engine"] == "sharded"
        assert m["wire_bytes_pruned"] > 0
        assert m["wire_bytes_pruned"] < m["wire_bytes_dense"]
        assert m["pruned_fraction"] > 0
        assert res.assignment == ref.assignment
        assert res.cost == ref.cost


# ---------------------------------------------------------------------------
# tracking: seeded walk, local mutations, zero-retrace warm churn
# ---------------------------------------------------------------------------


class TestTrackingFamily:
    def test_positions_pure_function_of_step(self):
        a = target_positions(3, 5, seed=7, side=6)
        b = target_positions(3, 5, seed=7, side=6)
        assert np.array_equal(a, b)
        assert not np.array_equal(a, target_positions(3, 6, seed=7,
                                                      side=6))
        assert not np.array_equal(a, target_positions(3, 5, seed=8,
                                                      side=6))

    def test_requires_square_grid(self):
        with pytest.raises(ValueError, match="square"):
            generate_tracking(10)

    def test_moved_constraint_same_scope_shape(self):
        dcop = generate_tracking(16, n_targets=2, seed=5)
        for name in step_mutations(dcop, 1):
            new_c = moved_constraint(dcop, name, 1)
            old = dcop.constraints[name]
            assert new_c.scope_names == old.scope_names
            assert (np.asarray(new_c.to_tensor()).shape
                    == np.asarray(old.to_tensor()).shape)

    def test_mgm_end_to_end(self):
        dcop = generate_tracking(16, n_targets=2, seed=5)
        res = solve_result(dcop, "mgm", cycles=60)
        assert res.status == "FINISHED"
        assert res.cost < 0  # tracking utility is negated gain

    def test_warm_repair_zero_retraces(self):
        """The acceptance pin: a tracking target-walk churn stream
        applied through the WarmRepairController costs ZERO retraces —
        every step is a fixed-shape EditFactor buffer write."""
        from pydcop_tpu.runtime.repair import WarmRepairController

        dcop = generate_tracking(16, n_targets=2, seed=9)
        scen = tracking_scenario(dcop, 4)
        ctl = WarmRepairController(dcop, "mgm", seed=0)
        res = ctl.solver.run(chunk=ctl.chunk, cycles=16)
        ctl.phase_done(res)
        applied = 0
        for event in scen:
            if event.is_delay:
                continue
            for action in event.actions:
                p = action.parameters
                ctl.edit_factor(moved_constraint(
                    dcop, p["constraint"], int(p["step"])
                ))
                applied += 1
            res = ctl.solver.run(resume=True, cycles=16,
                                 chunk=ctl.chunk)
            ctl.phase_done(res)
        c = ctl.counters.as_dict()
        assert applied > 0
        assert c["mutations_applied"] == applied
        assert c["repair_retraces"] == 0, c
        assert c["time_to_recover_s"] > 0


# ---------------------------------------------------------------------------
# serve admission: both families through the streaming front door
# ---------------------------------------------------------------------------


class TestServeAdmission:
    def test_new_families_through_solve_service(self):
        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.serve import SolveService

        routing = generate_routing(10, n_slots=4, seed=3)
        tracking = generate_tracking(16, n_targets=2, seed=5)
        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=80)
        jids = [svc.submit(routing, "mgm", seed=0),
                svc.submit(tracking, "mgm", seed=1)]
        for _ in range(500):
            if not svc.tick():
                break
        for jid, dcop in zip(jids, (routing, tracking)):
            res = svc.result(jid, timeout=5)
            assert res.status == "FINISHED"
            ref = solve_result(dcop, "mgm",
                               seed=jids.index(jid))
            assert res.cost == ref.cost
            assert res.assignment == ref.assignment


# ---------------------------------------------------------------------------
# the SLO ladder
# ---------------------------------------------------------------------------


def _tiers():
    return (
        TierSpec("gold", 2, 10.0, 0.99, 0.25),
        TierSpec("silver", 1, 5.0, 0.90, 0.25),
        TierSpec("bronze", 0, 20.0, 0.50, 0.50),
    )


class TestSloLadder:
    def test_escalates_one_rung_per_breached_eval(self):
        lad = SloLadder(_tiers(), min_samples=2, hold=2)
        for _ in range(3):
            lad.record("silver", False)
        assert lad.evaluate() == 1 and lad.shed_bronze
        # windows reset on escalation: no data → no breach → clean
        assert lad.evaluate() == 1
        for _ in range(2):
            lad.record("silver", False)
        assert lad.evaluate() == 2 and lad.clamp_silver
        for _ in range(2):
            lad.record("gold", False)
        assert lad.evaluate() == 3 and lad.reroute_gold
        # rung is capped at the top
        for _ in range(2):
            lad.record("gold", False)
        assert lad.evaluate() == 3
        c = lad.counters.as_dict()
        assert c["ladder_escalations"] == 3
        assert c["tier_breaches"] >= 3

    def test_releases_with_hysteresis(self):
        lad = SloLadder(_tiers(), min_samples=2, hold=3)
        for _ in range(2):
            lad.record("silver", False)
        assert lad.evaluate() == 1
        # two clean evaluations are not enough (hold=3)
        assert lad.evaluate() == 1
        assert lad.evaluate() == 1
        assert lad.evaluate() == 0
        assert lad.counters.counts["ladder_deescalations"] == 1

    def test_below_min_samples_never_breaches(self):
        lad = SloLadder(_tiers(), min_samples=4, hold=2)
        for _ in range(3):
            lad.record("gold", False)
        assert lad.evaluate() == 0

    def test_disabled_ladder_accounts_but_never_moves(self):
        lad = SloLadder(_tiers(), min_samples=2, enabled=False)
        for _ in range(4):
            lad.record("gold", False)
        assert lad.evaluate() == 0
        assert lad.counters.counts["tier_breaches"] > 0
        assert lad.counters.counts["ladder_escalations"] == 0

    def test_events_emitted(self):
        seen = []
        cb = lambda t, e: seen.append(t)  # noqa: E731
        event_bus.enabled = True
        event_bus.subscribe("slo.*", cb)
        try:
            lad = SloLadder(_tiers(), min_samples=2, hold=1)
            lad.record("silver", False)
            lad.record("silver", False)
            lad.evaluate()  # breach + escalate
            lad.evaluate()  # clean → release (hold=1)
        finally:
            event_bus.unsubscribe(cb)
            event_bus.enabled = False
        assert "slo.tier.breach" in seen
        assert "slo.ladder.escalated" in seen
        assert "slo.ladder.released" in seen

    def test_unknown_slo_counter_rejected(self):
        with pytest.raises(KeyError):
            SloCounters().inc("nope")


# ---------------------------------------------------------------------------
# serve-layer levers: deadline pressure, tenant drops, emptiest routing
# ---------------------------------------------------------------------------


class TestDeadlinePressure:
    def test_pressure_scales_clamp_for_non_exempt_lanes(self):
        """With pressure f, a non-exempt deadline lane's chunk budget
        is clamp(remaining * f * rate); an exempt (gold) lane keeps
        its full budget."""
        from time import monotonic

        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.serve import SolveService

        dcop = generate_routing(10, n_slots=4, seed=3)
        svc = SolveService(lanes=2, cache=CompileCache(),
                           max_cycles=400)
        svc.set_deadline_pressure(1e-6, exempt_priority=2)
        jid = svc.submit(dcop, "mgm", seed=0, priority=0,
                         deadline_s=120.0)
        svc.tick()  # first step measures the rate
        (w,) = svc._workers
        assert w.deadline_pressure == 1e-6
        assert w.pressure_exempt_priority == 2
        for _ in range(3):
            svc.tick()
        # clamped chunks: remaining*1e-6 seconds of budget → 1-cycle
        # chunks, counted
        assert svc.counters.counts["deadline_shrunk_lanes"] > 0
        # restoring pressure lets the job finish normally — and mgm's
        # coin-free stream makes the result independent of the chunk
        # boundaries the clamp introduced
        svc.set_deadline_pressure(1.0)
        for _ in range(500):
            if not svc.tick():
                break
        res = svc.result(jid, timeout=5)
        ref = solve_result(dcop, "mgm", seed=0)
        assert res.status == "FINISHED"
        assert res.cost == ref.cost
        assert res.assignment == ref.assignment

    def test_pressure_applies_to_later_buckets_too(self):
        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.serve import SolveService

        svc = SolveService(lanes=2, cache=CompileCache())
        svc.set_deadline_pressure(0.5, exempt_priority=1)
        dcop = generate_routing(10, n_slots=4, seed=4)
        svc.submit(dcop, "mgm", seed=0)
        svc.tick()
        (w,) = svc._workers
        assert w.deadline_pressure == 0.5
        assert w.pressure_exempt_priority == 1


class TestTenantDropAttribution:
    def test_events_dropped_by_tenant(self):
        from pydcop_tpu.runtime.stats import ServeCounters
        from pydcop_tpu.serve.service import ServeJob

        counters = ServeCounters()
        job = ServeJob(
            jid="j1", dcop=None, algo="mgm", algo_params={}, seed=0,
            tenant="gold", priority=2, deadline_s=None,
            deadline_at=None, label=None, source_file=None,
            stream=True, submitted_at=0.0, seq=1, counters=counters,
        )
        job.events = queue.Queue(maxsize=1)
        job.emit("job.progress", {"cycle": 1})
        job.emit("job.progress", {"cycle": 2})  # dropped
        job.emit("job.progress", {"cycle": 3})  # dropped
        assert counters.counts["events_dropped"] == 2
        assert counters.as_dict()["events_dropped_by_tenant"] == {
            "gold": 2
        }

    def test_surfaced_in_service_metrics(self):
        from pydcop_tpu.batch.cache import CompileCache
        from pydcop_tpu.serve import SolveService

        svc = SolveService(lanes=2, cache=CompileCache())
        svc.counters.drop_event("gold")
        m = svc.metrics()["serve"]
        assert m["events_dropped_by_tenant"] == {"gold": 1}


class TestEmptiestPlacement:
    def test_prefer_emptiest_beats_warm_affinity(self):
        from pydcop_tpu.serve.router import FleetRouter

        r = FleetRouter()
        r.add_replica("replica-0")
        r.add_replica("replica-1")
        key = ("mgm", (), "constraints_hypergraph", (2,))
        r.note_warm("replica-0", key)
        for _ in range(3):
            r.job_placed("replica-0")
        # warm-first policy sticks to the loaded warm replica
        name, warm = r.place(key)
        assert name == "replica-0" and warm
        r.job_finished("replica-0")
        # emptiest policy ignores warmth: the idle cold peer wins
        name, warm = r.place(key, prefer_emptiest=True)
        assert name == "replica-1" and not warm

    def test_emptiest_skips_unhealthy(self):
        from pydcop_tpu.serve.router import FleetRouter

        r = FleetRouter()
        r.add_replica("replica-0")
        r.add_replica("replica-1")
        r.set_stalled("replica-1", True)  # emptiest but unhealthy
        r.job_placed("replica-0")
        key = ("mgm", (), "constraints_hypergraph", (2,))
        name, _warm = r.place(key, prefer_emptiest=True)
        assert name == "replica-0"

    def test_fleet_placement_and_pressure_passthrough(self):
        from pydcop_tpu.serve import SolveFleet

        dcop = generate_routing(10, n_slots=4, seed=3)
        fleet = SolveFleet(replicas=2, lanes=2, max_cycles=80)
        try:
            fleet.set_deadline_pressure(0.25, exempt_priority=2)
            for h in fleet._handles.values():
                assert h.service._deadline_pressure == (0.25, 2)
            jid = fleet.submit(dcop, "mgm", seed=0,
                               placement="emptiest")
            for _ in range(500):
                if not fleet.tick():
                    break
            res = fleet.result(jid, timeout=5)
            assert res.status == "FINISHED"
        finally:
            fleet.stop(drain=False)


# ---------------------------------------------------------------------------
# the twin runner
# ---------------------------------------------------------------------------


def _small_jobs(n=6, seed=7, tiers=None):
    return build_twin_traffic(
        n, tiers if tiers is not None else default_tiers(),
        seed=seed, coloring_vars=24, routing_tasks=8,
        tracking_sensors=9,
    )


class TestTwinTraffic:
    def test_schedule_is_seeded_deterministic(self):
        a = _small_jobs(8, seed=3)
        b = _small_jobs(8, seed=3)
        assert [(j.tier, j.arrival_tick, j.family) for j in a] == \
               [(j.tier, j.arrival_tick, j.family) for j in b]
        c = _small_jobs(8, seed=4)
        assert [(j.tier, j.arrival_tick) for j in a] != \
               [(j.tier, j.arrival_tick) for j in c]

    def test_families_cycle_and_tiers_follow_shares(self):
        jobs = _small_jobs(9, seed=1)
        assert {j.family for j in jobs} == {
            "routing", "tracking", "coloring"
        }
        assert all(j.tier in ("gold", "silver", "bronze")
                   for j in jobs)


class TestTwinRunner:
    def test_clean_run_scores_everything(self):
        tiers = default_tiers()
        jobs = _small_jobs(6, seed=7, tiers=tiers)
        twin = TwinRunner(jobs, tiers, replicas=2, lanes=2,
                          max_cycles=80)
        card = twin.run(max_ticks=600)
        assert all(j.scored for j in twin.jobs)
        assert card["jobs"] == 6
        assert card["shed_rate"] == 0.0
        total = sum(
            t["scored"] for t in card["tiers"].values()
        )
        assert total == 6
        assert card["ladder"]["enabled"]
        assert card["fleet"]["replicas_down"] == 0

    def test_chaos_run_bitmatches_standalone(self):
        """The acceptance pin: under the combined chaos plan (kill +
        serve faults + churn), every FINISHED job equals its
        standalone solve bit for bit."""
        tiers = default_tiers()
        jobs = _small_jobs(6, seed=11, tiers=tiers)
        live = generate_tracking(16, n_targets=2, seed=12)
        scen = tracking_scenario(live, 3)
        plan = default_chaos_plan(seed=5, kill_tick=4)
        twin = TwinRunner(jobs, tiers, replicas=2, lanes=2,
                          max_cycles=80, fault_plan=plan,
                          live_dcop=live, live_scenario=scen)
        card = twin.run(max_ticks=800)
        assert card["fleet"]["replicas_down"] == 1
        assert card["fleet"]["faults_injected"] >= 1
        base = standalone_results(jobs, max_cycles=80)
        checked = 0
        for label, res in twin.results.items():
            if res.status != "FINISHED":
                continue
            checked += 1
            assert res.cost == base[label].cost, label
            assert res.assignment == base[label].assignment, label
        assert checked > 0
        # churn ran warm with zero retraces
        assert card["churn"]["repair_retraces"] == 0
        assert card["churn"]["mutations_applied"] > 0

    def test_ladder_rungs_pull_their_levers(self):
        """Force engagement with an unmeetable bronze deadline: the
        ladder must shed later bronze arrivals (rung 1), and release
        after the pressure clears."""
        tiers = (
            TierSpec("gold", 2, 30.0, 0.99, 0.2),
            TierSpec("silver", 1, 30.0, 0.90, 0.2),
            TierSpec("bronze", 0, 0.0001, 0.50, 0.6),
        )
        rng_jobs = _small_jobs(12, seed=2, tiers=tiers)
        twin = TwinRunner(
            rng_jobs, tiers, replicas=2, lanes=2, max_cycles=80,
            ladder_min_samples=2, ladder_hold=2, ladder_window=6,
        )
        card = twin.run(max_ticks=800)
        assert card["ladder"]["engaged"], card["slo"]
        assert card["slo"]["ladder_escalations"] >= 1
        # the run drains after the last completion: hysteresis clears
        assert card["ladder"]["released"], card["ladder"]
        assert card["ladder"]["final_rung"] == 0
        bronze = card["tiers"]["bronze"]
        if bronze["shed"]:
            assert card["slo"]["bronze_sheds"] == bronze["shed"]

    def test_scorecard_math(self):
        from pydcop_tpu.scenario import scorecard

        tiers = default_tiers()
        counters = SloCounters()
        scores = [
            JobScore("a", "gold", "gold", "FINISHED", 0.5, 30.0, True),
            JobScore("b", "gold", "gold", "TIMEOUT", 31.0, 30.0,
                     False),
            JobScore("c", "bronze", "bronze", "SHED", None, 20.0,
                     False, shed=True),
        ]
        card = scorecard(scores, tiers, counters, [0.02], [0.1, 0.3])
        assert card["tiers"]["gold"]["attainment"] == 0.5
        assert card["tiers"]["bronze"]["shed"] == 1
        assert card["tiers"]["bronze"]["attainment"] is None
        assert card["shed_rate"] == pytest.approx(1 / 3, abs=1e-4)
        assert card["rto_max_s"] == 0.02
        assert card["recover_s_mean"] == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# chaos-plan churn kinds through the twin
# ---------------------------------------------------------------------------


class TestTwinChurnFaults:
    def test_edit_factor_fault_applies_warm(self):
        tiers = default_tiers()
        jobs = _small_jobs(3, seed=4, tiers=tiers)
        live = generate_tracking(9, n_targets=2, seed=6)
        plan = FaultPlan(faults=[
            Fault(kind="edit_factor", cycle=1),
            Fault(kind="remove_agent_burst", cycle=2, count=1),
        ], seed=9)
        twin = TwinRunner(jobs, tiers, replicas=1, lanes=2,
                          max_cycles=60, fault_plan=plan,
                          live_dcop=live, churn_start=1,
                          churn_every=1)
        card = twin.run(max_ticks=600)
        assert card["churn"]["mutations_applied"] >= 1
        assert card["churn"]["repair_retraces"] == 0
        assert len(card["recover_s"]) >= 2