"""End-to-end tests for the local-search family + async/dynamic variants."""
import os

import pytest

from pydcop_tpu.dcop import load_dcop, load_dcop_from_file
from pydcop_tpu.runtime import solve_result

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def csp_dcop():
    return load_dcop_from_file(os.path.join(INSTANCES, "coloring_csp.yaml"))


@pytest.fixture
def tuto_dcop():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


@pytest.mark.parametrize(
    "algo", ["dsa", "dsatuto", "mgm", "mgm2", "dba", "gdba", "mixeddsa",
             "adsa"]
)
def test_solves_csp_triangle(csp_dcop, algo):
    """All local-search algorithms 3-color the triangle (cost 0)."""
    res = solve_result(csp_dcop, algo, cycles=50, seed=3)
    assert res.violation == 0
    assert res.cost == 0
    assert len(set(res.assignment.values())) == 3


@pytest.mark.parametrize("algo", ["dsa", "mgm", "mgm2"])
def test_tuto_reaches_low_cost(tuto_dcop, algo):
    res = solve_result(tuto_dcop, algo, cycles=30, seed=1)
    # optimum is 12; all-R (cost 18) is the worst single-move local optimum
    assert res.cost <= 18
    assert res.violation == 0


def test_mgm_monotone(tuto_dcop):
    res = solve_result(
        tuto_dcop, "mgm", cycles=20, seed=5, collect_cycles=True
    )
    costs = [h["cost"] for h in res.history]
    for a, b in zip(costs, costs[1:]):
        assert b <= a + 1e-5


def test_mgm2_beats_or_matches_mgm_on_average(tuto_dcop):
    """MGM-2's coordinated moves escape some of MGM's local minima."""
    mgm_costs, mgm2_costs = [], []
    for seed in range(4):
        mgm_costs.append(
            solve_result(tuto_dcop, "mgm", cycles=25, seed=seed).cost
        )
        mgm2_costs.append(
            solve_result(tuto_dcop, "mgm2", cycles=25, seed=seed).cost
        )
    assert sum(mgm2_costs) <= sum(mgm_costs) + 1e-6


def test_dsa_variants(csp_dcop):
    for variant in ("A", "B", "C"):
        res = solve_result(
            csp_dcop, "dsa", cycles=60,
            algo_params={"variant": variant, "probability": 0.7}, seed=2,
        )
        assert res.cost == 0, variant


def test_gdba_modes(csp_dcop):
    for modifier in ("A", "M"):
        for increase_mode in ("E", "R", "C", "T"):
            res = solve_result(
                csp_dcop, "gdba", cycles=40,
                algo_params={
                    "modifier": modifier, "increase_mode": increase_mode
                },
                seed=1,
            )
            assert res.cost == 0, (modifier, increase_mode)


def test_amaxsum(tuto_dcop):
    res = solve_result(tuto_dcop, "amaxsum", cycles=40, seed=0)
    assert res.assignment == {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}


def test_maxsum_dynamic_factor_change():
    from pydcop_tpu.algorithms import AlgorithmDef
    from pydcop_tpu.algorithms.maxsum_dynamic import build_solver
    from pydcop_tpu.dcop import constraint_from_str

    dcop = load_dcop(
        """
name: dyn
domains: {d: {values: [0, 1]}}
variables:
  v1: {domain: d}
  v2: {domain: d}
constraints:
  c1: {type: intention, function: 10 if v1 != v2 else 0}
agents: [a1, a2]
"""
    )
    solver = build_solver(dcop)
    res = solver.run(cycles=10)
    assert res.assignment["v1"] == res.assignment["v2"]
    # flip the factor: now equality is penalized
    new_c = constraint_from_str(
        "c1", "10 if v1 == v2 else 0", dcop.variables.values()
    )
    solver.change_factor_function(new_c)
    res2 = solver.run(cycles=10)
    assert res2.assignment["v1"] != res2.assignment["v2"]


def test_maxsum_dynamic_external_change():
    from pydcop_tpu.algorithms.maxsum_dynamic import build_solver

    dcop = load_dcop(
        """
name: dyn_ext
domains: {d: {values: [0, 1]}}
variables:
  v1: {domain: d}
external_variables:
  e1: {domain: d, initial_value: 0}
constraints:
  c1: {type: intention, function: 10 if v1 != e1 else 0}
agents: [a1]
"""
    )
    solver = build_solver(dcop)
    assert solver.run(cycles=5).assignment["v1"] == 0
    solver.on_external_change("e1", 1)
    assert solver.run(cycles=5).assignment["v1"] == 1
