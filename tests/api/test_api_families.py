"""Solve generated problem families end-to-end (SECP, Ising, meetings,
IoT, smallworld) — the reference's benchmark configurations (BASELINE.md)."""
import pytest

from pydcop_tpu.generators import (
    generate_graph_coloring,
    generate_iot,
    generate_ising,
    generate_meeting_scheduling,
    generate_secp,
    generate_smallworld,
)
from pydcop_tpu.runtime import solve_result


def test_secp_dpop_optimal():
    """SECP smart lighting solved with DPOP (reference config #4)."""
    dcop = generate_secp(n_lights=5, n_models=2, n_rules=2,
                         light_levels=3, seed=1)
    res = solve_result(dcop, "dpop")
    assert res.status == "FINISHED"
    assert res.violation == 0
    # cross-check with another complete algorithm
    res2 = solve_result(dcop, "ncbb")
    assert res.cost == pytest.approx(res2.cost)


def test_secp_maxsum_near_optimal():
    dcop = generate_secp(n_lights=5, n_models=2, n_rules=2,
                         light_levels=3, seed=1)
    opt = solve_result(dcop, "dpop").cost
    res = solve_result(dcop, "maxsum", cycles=30)
    assert res.cost <= opt * 1.5 + 1.0


def test_ising_maxsum():
    """Ising grid BP (reference config #3)."""
    dcop, _, _ = generate_ising(4, 4, seed=2)
    # BP oscillates on frustrated grids (docs/performance.rst, the
    # stretch convergence study): at 40 cycles the anytime assignment
    # still rides an oscillation crest (-11.96 measured); by 200 it has
    # visited the DSA-reachable basin (-14.35)
    res = solve_result(dcop, "maxsum", cycles=200)
    assert res.status == "FINISHED"
    assert res.violation == 0
    # BP on the frustrated grid should land near the DSA-reachable level
    res_dsa = solve_result(dcop, "dsa", cycles=60, seed=1)
    assert res.cost <= res_dsa.cost + 2.0


def test_coloring_100vars_dsa_mgm():
    """Random 100-var coloring with DSA-B and MGM (reference config #2)."""
    dcop = generate_graph_coloring(
        100, n_colors=3, n_edges=200, soft=True, seed=5
    )
    start = solve_result(dcop, "mgm", cycles=1, seed=2).cost
    mgm = solve_result(dcop, "mgm", cycles=30, seed=2)
    dsa = solve_result(dcop, "dsa", cycles=30, seed=2)
    assert mgm.cost < start  # monotone descent actually happened
    assert dsa.cost < start
    assert mgm.status == dsa.status == "FINISHED"


def test_meetings_complete_consistent():
    dcop = generate_meeting_scheduling(
        n_agents=3, n_meetings=2, n_slots=4, seed=3
    )
    res = solve_result(dcop, "dpop")
    assert res.violation == 0
    # meeting copies must agree (hard equality constraints satisfied)
    values = res.assignment
    by_meeting = {}
    for name, v in values.items():
        m = name.split("_")[0]
        by_meeting.setdefault(m, set()).add(v)
    for m, vals in by_meeting.items():
        assert len(vals) == 1, f"meeting {m} copies disagree: {vals}"


def test_iot_and_smallworld_solvable():
    for dcop in (generate_iot(8, seed=1), generate_smallworld(12, seed=1)):
        res = solve_result(dcop, "mgm2", cycles=25, seed=0)
        assert res.status == "FINISHED"
        assert res.violation == 0
