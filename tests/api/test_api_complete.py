"""End-to-end tests for the complete algorithms: DPOP, SyncBB, NCBB.

All three are exact — they must return the true optimum on every instance,
cross-checked against brute force.
"""
import itertools
import os

import pytest

from pydcop_tpu.dcop import load_dcop, load_dcop_from_file
from pydcop_tpu.runtime import solve_result

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")

COMPLETE_ALGOS = ["dpop", "syncbb", "ncbb"]


def brute_force(dcop):
    best, best_cost = None, float("inf")
    names = sorted(dcop.variables)
    domains = [list(dcop.variables[n].domain) for n in names]
    for combo in itertools.product(*domains):
        asst = dict(zip(names, combo))
        _, cost = dcop.solution_cost(asst, 10000000)
        if cost < best_cost:
            best, best_cost = asst, cost
    return best, best_cost


@pytest.fixture
def tuto_dcop():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


@pytest.fixture
def intention_dcop():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "coloring_intention.yaml")
    )


@pytest.mark.parametrize("algo", COMPLETE_ALGOS)
def test_tuto_optimum(tuto_dcop, algo):
    res = solve_result(tuto_dcop, algo)
    assert res.cost == 12
    assert res.assignment == {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}
    assert res.status == "FINISHED"
    assert res.msg_count > 0


@pytest.mark.parametrize("algo", COMPLETE_ALGOS)
def test_intention_with_variable_costs(intention_dcop, algo):
    _, expected = brute_force(intention_dcop)
    res = solve_result(intention_dcop, algo)
    assert res.cost == pytest.approx(expected)


@pytest.mark.parametrize("algo", COMPLETE_ALGOS)
def test_random_weighted_instances(algo):
    """Cross-check optimality on random weighted binary instances."""
    import random

    rng = random.Random(42)
    for trial in range(3):
        n, d = 5, 3
        lines = [
            "name: rnd", "objective: min",
            "domains: {dom: {values: [0, 1, 2]}}", "variables:",
        ]
        for i in range(n):
            lines.append(f"  v{i}: {{domain: dom}}")
        lines.append("constraints:")
        cnum = 0
        for i in range(n):
            for j in range(i + 1, n):
                if rng.random() < 0.6:
                    w1, w2 = rng.randint(0, 9), rng.randint(0, 9)
                    lines.append(
                        f"  c{cnum}: {{type: intention, function: "
                        f"'{w1} if v{i} == v{j} else {w2} * abs(v{i} - v{j})'}}"
                    )
                    cnum += 1
        lines.append("agents: [a1]")
        dcop = load_dcop("\n".join(lines))
        _, expected = brute_force(dcop)
        res = solve_result(dcop, algo)
        assert res.cost == pytest.approx(expected), f"trial {trial}"


@pytest.mark.parametrize("algo", COMPLETE_ALGOS)
def test_max_mode(algo):
    dcop = load_dcop(
        """
name: maxtest
objective: max
domains: {d: {values: [0, 1, 2]}}
variables:
  v1: {domain: d}
  v2: {domain: d}
  v3: {domain: d}
constraints:
  c1: {type: intention, function: v1 + v2 if v1 != v2 else 0}
  c2: {type: intention, function: 2 * v3 - v2}
agents: [a1]
"""
    )
    res = solve_result(dcop, algo)
    # brute force in max mode
    best = max(
        (a + b if a != b else 0) + 2 * c - b
        for a in range(3) for b in range(3) for c in range(3)
    )
    assert res.cost == pytest.approx(best)


def test_dpop_message_metrics(tuto_dcop):
    res = solve_result(tuto_dcop, "dpop")
    # 4 vars → 3 UTIL messages in a 4-node tree (+ VALUE msgs)
    assert res.msg_count >= 3
    assert res.msg_size > 0


def test_syncbb_disconnected():
    dcop = load_dcop(
        """
name: disc
domains: {d: {values: [0, 1]}}
variables:
  a1v: {domain: d}
  a2v: {domain: d}
  b1v: {domain: d}
  b2v: {domain: d}
constraints:
  ca: {type: intention, function: 5 if a1v == a2v else 1}
  cb: {type: intention, function: 3 if b1v != b2v else 2}
agents: [ag1]
"""
    )
    for algo in COMPLETE_ALGOS:
        res = solve_result(dcop, algo)
        assert res.cost == 3  # 1 + 2
