"""Process-mode library API: run_local_process_dcop spawns real OS
processes (gloo mesh ranks) and must produce the same result as thread
mode (reference contract: pydcop/infrastructure/run.py:225-287)."""
import os

import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime import run_local_process_dcop, run_local_thread_dcop

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")


@pytest.fixture
def tuto():
    return load_dcop_from_file(TUTO)


def test_process_mode_matches_thread_mode(tuto):
    orch = run_local_process_dcop(
        tuto, "maxsum", distribution="adhoc", n_processes=2
    )
    try:
        res = orch.run(cycles=20)
    except RuntimeError as e:
        if "Multiprocess computations aren't implemented" in str(e):
            pytest.skip(
                "environment: this jaxlib's CPU backend lacks "
                "multi-process (Gloo) collectives, so 2-rank process "
                "mode cannot form a mesh on this host (see "
                "tests/unit/test_multihost.py assert_rank_ok)"
            )
        raise
    finally:
        orch.stop()
    assert res.status == "FINISHED"
    # two real processes formed one global mesh (each contributes the
    # same number of local devices — 8 virtual CPU devices under the
    # test conftest's XLA_FLAGS, 1 otherwise)
    assert orch.n_global_devices >= 2
    assert orch.n_global_devices % 2 == 0
    assert orch.end_metrics()["n_processes"] == 2

    thread = run_local_thread_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc"
    )
    res_thread = thread.run(cycles=20)
    assert res.assignment == res_thread.assignment
    assert res.cost == res_thread.cost


def test_process_mode_rejects_host_driven_algos(tuto):
    with pytest.raises(ValueError, match="process mode"):
        run_local_process_dcop(tuto, "dpop")


def test_process_mode_rejects_dynamic_scenarios(tuto):
    from pydcop_tpu.dcop import DcopEvent, Scenario

    orch = run_local_process_dcop(
        tuto, "maxsum", distribution="adhoc", n_processes=2
    )
    try:
        scenario = Scenario([DcopEvent("d1", delay=1)])
        with pytest.raises(ValueError, match="thread mode"):
            orch.run(scenario, cycles=5)
    finally:
        orch.stop()


def test_process_mode_requires_deploy(tuto):
    from pydcop_tpu.runtime.process import ProcessOrchestrator

    orch = ProcessOrchestrator(tuto, "maxsum", distribution="adhoc")
    with pytest.raises(RuntimeError, match="deploy"):
        orch.run(cycles=2)
