"""Process-mode crash resilience, end to end with REAL OS processes.

The acceptance scenario of the fault-tolerance layer: under a fault
plan that kills a rank mid-run, the job neither hangs nor orphans
processes — the watchdog detects the failure within its timeout, the
relaunch resumes from the latest checkpoint, and the final cost
matches the fault-free run on the same seed.

These tests run with ``n_processes=1`` deliberately: a 1-rank mesh
exercises the ENTIRE resilience machinery (rank spawn, heartbeats,
injected kill/stall, watchdog classification, backoff relaunch,
checkpoint save/auto-resume) while staying runnable on jaxlib builds
whose CPU backend lacks multi-process collectives (this image — see
tests/api/test_api_process.py baseline).  The 2-process variant is
marked slow and additionally tolerates the degrade-to-thread path on
such builds.
"""
import os
import subprocess

import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime import run_local_process_dcop
from pydcop_tpu.runtime.faults import Fault, FaultPlan

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")
TUTO = os.path.join(INSTANCES, "graph_coloring_tuto.yaml")


def _no_leftover_ranks(orch):
    """No orphan processes: every spawned rank has been reaped."""
    assert orch._procs == []
    out = subprocess.run(
        ["pgrep", "-f", "pydcop_tpu.*--multihost"],
        capture_output=True, text=True,
    )
    assert out.stdout.strip() == "", f"orphan ranks: {out.stdout}"


@pytest.fixture
def fault_free_result():
    orch = run_local_process_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc",
        n_processes=1,
    )
    try:
        res = orch.run(cycles=20)
    finally:
        orch.stop()
    assert res.status == "FINISHED"
    return res


def test_killed_rank_recovers_from_checkpoint(fault_free_result):
    """Kill rank 0 at cycle 8 (first launch only): the watchdog sees
    the injected exit code, relaunches after backoff, the rank resumes
    from the cycle-8 snapshot and the final cost/assignment match the
    fault-free run exactly."""
    plan = FaultPlan(
        faults=[Fault(kind="kill_rank", rank=0, cycle=8)]
    )
    orch = run_local_process_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc",
        n_processes=1, fault_plan=plan, checkpoint_every=4,
        max_retries=2, backoff_base=0.1,
    )
    try:
        res = orch.run(cycles=20)
        m = orch.end_metrics()
    finally:
        orch.stop()
    assert res.status == "FINISHED"
    r = m["resilience"]
    assert r["rank_crashes"] == 1
    assert r["faults_injected"] >= 1
    assert r["retries"] == 1
    assert r["resumes"] == 1
    assert r["degraded_to_thread"] == 0
    assert m["fault_log"][0]["fault"] == "crash"
    # identical final answer to the fault-free run on the same seed
    assert res.cost == fault_free_result.cost
    assert res.assignment == fault_free_result.assignment
    _no_leftover_ranks(orch)


def test_stalled_rank_detected_and_recovered(fault_free_result):
    """SIGSTOP rank 0 for 60s at cycle 4: heartbeats go stale, the
    watchdog declares a stall well before the 60s elapse (bounded
    detection), kills the frozen rank and relaunches — no hang."""
    plan = FaultPlan(
        faults=[Fault(kind="stall_rank", rank=0, cycle=4, duration=60)]
    )
    orch = run_local_process_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc",
        n_processes=1, fault_plan=plan, checkpoint_every=4,
        max_retries=2, backoff_base=0.1, stall_timeout=3.0,
    )
    try:
        res = orch.run(cycles=20)
        m = orch.end_metrics()
    finally:
        orch.stop()
    assert res.status == "FINISHED"
    assert m["resilience"]["rank_stalls"] == 1
    assert m["resilience"]["retries"] == 1
    assert res.cost == fault_free_result.cost
    _no_leftover_ranks(orch)


def test_degrades_to_thread_after_max_retries(fault_free_result):
    """A kill that fires on EVERY attempt exhausts max_retries; the
    orchestrator then degrades to thread mode and still produces the
    correct result instead of failing the caller."""
    plan = FaultPlan(
        faults=[Fault(kind="kill_rank", rank=0, cycle=4, attempt=None)]
    )
    orch = run_local_process_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc",
        n_processes=1, fault_plan=plan, max_retries=1, backoff_base=0.1,
    )
    try:
        res = orch.run(cycles=20)
        m = orch.end_metrics()
    finally:
        orch.stop()
    assert res.status == "FINISHED"
    assert m["resilience"]["degraded_to_thread"] == 1
    assert m["resilience"]["rank_crashes"] == 2  # initial + 1 retry
    assert res.cost == fault_free_result.cost
    _no_leftover_ranks(orch)


def test_no_retry_on_deterministic_error(tmp_path):
    """A rank that fails with a clean nonzero exit (deterministic
    error, here an unloadable DCOP file) raises immediately — no
    backoff retries that would only hide a reproducible bug."""
    orch = run_local_process_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc",
        n_processes=1, max_retries=5, backoff_base=30.0,
    )
    # sabotage the serialized DCOP the ranks load (clean rank error)
    with open(orch._dcop_file, "w") as f:
        f.write("{not yaml: [")
    from time import perf_counter
    t0 = perf_counter()
    try:
        with pytest.raises(RuntimeError, match="rank failed"):
            orch.run(cycles=5)
    finally:
        orch.stop()
    # immediate: nothing slept through 30s backoff steps
    assert perf_counter() - t0 < 25
    assert orch.fault_counters.counts["retries"] == 0
    _no_leftover_ranks(orch)


def test_corrupt_checkpoint_fault_resumes_from_older(fault_free_result):
    """Kill at cycle 12 + corrupt the newest snapshot before the
    relaunch: the rank must skip the damaged file, resume from an older
    snapshot and still land on the fault-free answer."""
    plan = FaultPlan(
        faults=[
            Fault(kind="kill_rank", rank=0, cycle=12),
            Fault(kind="corrupt_checkpoint", attempt=1),
        ],
        seed=3,
    )
    orch = run_local_process_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc",
        n_processes=1, fault_plan=plan, checkpoint_every=4,
        max_retries=2, backoff_base=0.1,
    )
    try:
        res = orch.run(cycles=20)
        m = orch.end_metrics()
    finally:
        orch.stop()
    assert res.status == "FINISHED"
    assert m["resilience"]["resumes"] == 1
    # the checkpoint fault itself was logged
    assert any(e.get("fault") == "checkpoint" for e in m["fault_log"])
    assert res.cost == fault_free_result.cost
    assert res.assignment == fault_free_result.assignment
    _no_leftover_ranks(orch)


@pytest.mark.slow
def test_two_process_crash_recovery():
    """The same kill/recover scenario on a REAL 2-process mesh.  On
    jaxlib builds whose CPU backend implements multi-process
    collectives the mesh recovers in process mode; on builds that lack
    them every attempt fails deterministically and the test instead
    asserts the raise-immediately contract — either way: no hang, no
    orphans."""
    plan = FaultPlan(
        faults=[Fault(kind="kill_rank", rank=1, cycle=8)]
    )
    orch = run_local_process_dcop(
        load_dcop_from_file(TUTO), "maxsum", distribution="adhoc",
        n_processes=2, fault_plan=plan, checkpoint_every=4,
        max_retries=2, backoff_base=0.1,
    )
    try:
        try:
            res = orch.run(cycles=20)
        except RuntimeError as e:
            assert "rank failed" in str(e)  # deterministic backend gap
        else:
            assert res.status in ("FINISHED", "TIMEOUT")
    finally:
        orch.stop()
    _no_leftover_ranks(orch)
