"""End-to-end library-API solve tests (reference twin: tests/api/).

Mirrors the reference's strategy (tests/api/test_api_solve.py:37-90): solve
small known instances and assert the assignment / cost.
"""
import os

import pytest

from pydcop_tpu.dcop import load_dcop_from_file
from pydcop_tpu.runtime import solve, solve_result

INSTANCES = os.path.join(os.path.dirname(__file__), "..", "instances")


@pytest.fixture
def tuto_dcop():
    return load_dcop_from_file(
        os.path.join(INSTANCES, "graph_coloring_tuto.yaml")
    )


@pytest.fixture
def csp_dcop():
    return load_dcop_from_file(os.path.join(INSTANCES, "coloring_csp.yaml"))


class TestMaxsum:
    def test_tuto_optimum(self, tuto_dcop):
        assignment = solve(tuto_dcop, "maxsum", timeout=10)
        assert assignment == {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}

    def test_result_metrics(self, tuto_dcop):
        res = solve_result(tuto_dcop, "maxsum", timeout=10)
        assert res.status == "FINISHED"
        assert res.cost == 12
        assert res.violation == 0
        assert res.cycle > 0
        assert res.msg_count > 0
        m = res.metrics()
        assert set(m) == {
            "status", "assignment", "cost", "violation", "cycle",
            "msg_count", "msg_size", "time", "harness", "config",
        }
        # the harness scorecard rides along for chunked tensor solves
        assert m["harness"]["chunks_dispatched"] > 0
        # ... as does the canonical executed-config record (ISSUE 10)
        assert m["config"]["algo"] == "maxsum"
        assert m["config"]["engine"] == "harness"

    def test_csp(self, csp_dcop):
        res = solve_result(csp_dcop, "maxsum", timeout=10)
        # 3-coloring of a triangle: all different
        vals = list(res.assignment.values())
        assert len(set(vals)) == 3
        assert res.cost == 0

    def test_stop_cycle(self, tuto_dcop):
        res = solve_result(tuto_dcop, "maxsum", cycles=5)
        assert res.cycle == 5

    def test_algo_params(self, tuto_dcop):
        assignment = solve(
            tuto_dcop, "maxsum", algo_params={"damping": 0.0}, timeout=10
        )
        assert assignment == {"v1": "G", "v2": "G", "v3": "G", "v4": "G"}

    def test_collect_cycles(self, tuto_dcop):
        res = solve_result(tuto_dcop, "maxsum", cycles=6, collect_cycles=True)
        assert len(res.history) == 6
        assert {"cycle", "cost", "time"} <= set(res.history[0])

    def test_with_distribution(self, tuto_dcop):
        # oneagent needs >= as many agents as computations (8 comps, 5 agts)
        from pydcop_tpu.distribution import ImpossibleDistributionException

        with pytest.raises(ImpossibleDistributionException):
            solve(tuto_dcop, "maxsum", distribution="oneagent", cycles=2)


class TestMaxMode:
    def test_maximize(self):
        from pydcop_tpu.dcop import load_dcop

        dcop = load_dcop(
            """
name: maxtest
objective: max
domains: {d: {values: [0, 1, 2]}}
variables:
  v1: {domain: d}
  v2: {domain: d}
constraints:
  c1: {type: intention, function: v1 + v2 if v1 != v2 else 0}
agents: [a1, a2]
"""
        )
        res = solve_result(dcop, "maxsum", timeout=10)
        assert res.cost == 3  # v1,v2 = {1,2} in some order
        assert sorted(res.assignment.values()) == [1, 2]
